"""Speculative decoding (prompt-lookup drafting) vs greedy generate().

The contract under test is EXACTNESS: `generate_speculative` must be
bit-identical to `generate(temperature=0)` for every model family and
acceptance pattern — matching drafts, mismatching drafts, and the
mixed-batch case where rows accept different lengths (min-over-batch
acceptance). Speed is the chip bench's job
(`benchmarks/specdecode_bench.py`); here we only assert the mechanism's telemetry moves the
right way on text the draft CAN predict (a learned periodic sequence).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pddl_tpu.data.synthetic import SyntheticLanguageModeling
from pddl_tpu.models.gpt import generate, tiny_gpt
from pddl_tpu.models.llama import tiny_llama
from pddl_tpu.models.speculative import generate_speculative
from pddl_tpu.parallel.mirrored import MirroredStrategy
from pddl_tpu.train.loop import Trainer


def _rand_prompt(key, b, p, vocab):
    return jax.random.randint(jax.random.key(key), (b, p), 0, vocab,
                              dtype=jnp.int32)


def _repetitive_prompt(b, p, vocab):
    """A strongly periodic prompt: the n-gram lookup fires constantly,
    so acceptance logic (full, partial, rewind) is exercised hard."""
    period = jnp.arange(7, dtype=jnp.int32) % vocab
    row = jnp.tile(period, p // 7 + 1)[:p]
    return jnp.broadcast_to(row, (b, p)).astype(jnp.int32)


@pytest.mark.parametrize("factory", [tiny_gpt, tiny_llama],
                         ids=["gpt", "llama-gqa"])
@pytest.mark.parametrize("prompt_kind", ["random", "repetitive"])
def test_speculative_matches_greedy(factory, prompt_kind):
    model = factory(vocab_size=32, max_len=96)
    prompt = (_rand_prompt(3, 2, 12, 32) if prompt_kind == "random"
              else _repetitive_prompt(2, 12, 32))
    variables = {"params": model.init(jax.random.key(0), prompt,
                                      train=False)["params"]}
    ref = generate(model, variables, prompt, max_new_tokens=40)
    out, stats = generate_speculative(model, variables, prompt, 40,
                                      draft_len=7, ngram=3,
                                      return_stats=True)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    assert out.shape == (2, 52)
    assert stats["emitted"] >= 40
    assert 1 <= stats["ticks"] <= 40
    assert stats["tokens_per_tick"] >= 1.0


@pytest.mark.parametrize("draft_len,ngram", [(1, 1), (3, 2), (15, 4)])
def test_speculative_exact_across_hyperparams(draft_len, ngram):
    """Exactness cannot depend on the draft configuration."""
    model = tiny_gpt(vocab_size=16, max_len=128)
    prompt = _repetitive_prompt(3, 9, 16)
    variables = {"params": model.init(jax.random.key(1), prompt,
                                      train=False)["params"]}
    ref = generate(model, variables, prompt, max_new_tokens=30)
    out = generate_speculative(model, variables, prompt, 30,
                               draft_len=draft_len, ngram=ngram)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_min_over_batch_acceptance_cost_is_the_worst_row():
    """Quantifies what shared-scalar-index acceptance costs beyond B1
    (VERDICT r5 item 3): the KV caches share ONE scalar index, so each
    batched tick emits min-over-rows acceptance + 1.

    A deliberately lopsided batch — a repetitive row the drafter nails
    next to a random row it can't — must (a) stay bit-exact per row
    against each row's SOLO run (truncation re-derives, never corrupts),
    and (b) pay the worst row's tick count: batched ticks >= the max of
    the solo tick counts, and >= the good row's solo ticks alone (the
    fast row is dragged down — THE measured cost `specdecode_bench.py
    --batches 1,4,8` quantifies at serving shapes)."""
    model = tiny_gpt(vocab_size=32, max_len=96)
    fast_row = _repetitive_prompt(1, 14, 32)
    slow_row = _rand_prompt(9, 1, 14, 32)
    batch = jnp.concatenate([fast_row, slow_row], axis=0)
    variables = {"params": model.init(jax.random.key(4), batch,
                                      train=False)["params"]}
    n_new = 36
    solo_stats = {}
    for name, row in (("fast", fast_row), ("slow", slow_row)):
        ref = generate(model, variables, row, max_new_tokens=n_new)
        out, stats = generate_speculative(model, variables, row, n_new,
                                          draft_len=7, ngram=3,
                                          return_stats=True)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
        solo_stats[name] = stats
    out_b, stats_b = generate_speculative(model, variables, batch, n_new,
                                          draft_len=7, ngram=3,
                                          return_stats=True)
    # (a) exactness: the batch emits exactly the stacked solo streams
    ref_b = generate(model, variables, batch, max_new_tokens=n_new)
    np.testing.assert_array_equal(np.asarray(out_b), np.asarray(ref_b))
    # (b) the min-over-batch price, pinned: the batch can never finish
    # in fewer ticks than its worst member, and the fast row's solo
    # rate is strictly better than what it gets inside the batch.
    assert stats_b["ticks"] >= max(s["ticks"] for s in solo_stats.values())
    assert (solo_stats["fast"]["tokens_per_tick"]
            >= stats_b["tokens_per_tick"])


def test_speculative_single_token_and_short_prompt():
    """Edge shapes: P=1 (n-gram underflows, clamped) and N=1 (one tick)."""
    model = tiny_gpt(vocab_size=16, max_len=64)
    prompt = jnp.full((2, 1), 5, jnp.int32)
    variables = {"params": model.init(jax.random.key(2), prompt,
                                      train=False)["params"]}
    for n_new in (1, 13):
        ref = generate(model, variables, prompt, max_new_tokens=n_new)
        out = generate_speculative(model, variables, prompt, n_new)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_speculative_accelerates_learned_sequence():
    """On a learned deterministic recurrence the drafts match and ticks
    collapse: the telemetry must show >1 token/tick, and the output must
    still equal plain greedy (which itself reproduces the recurrence —
    same bar as test_generate_continues_learned_sequence)."""
    ds = SyntheticLanguageModeling(batch_size=32, seq_len=32, vocab_size=16,
                                   seed=0)
    model = tiny_gpt(vocab_size=16, max_len=96)
    tr = Trainer(model, optimizer="adamw", learning_rate=3e-3,
                 strategy=MirroredStrategy(), seed=0,
                 input_key="tokens", target_key="targets")
    hist = tr.fit(ds, epochs=6, steps_per_epoch=8, verbose=0)
    assert hist.history["accuracy"][-1] > 0.95, hist.history["accuracy"]

    variables = {"params": jax.device_get(tr.state.params)}
    prompt = jnp.asarray(ds.batch(0)["tokens"][:4, :24])
    ref = generate(model, variables, prompt, max_new_tokens=48)
    out, stats = generate_speculative(model, variables, prompt, 48,
                                      return_stats=True)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    # The recurrence has period <= 16 < 24, so the lookup always finds the
    # pattern and a near-perfect model accepts near-full blocks.
    assert stats["tokens_per_tick"] > 2.0, stats


def test_speculative_validation_errors():
    model = tiny_gpt(vocab_size=16, max_len=32)
    prompt = jnp.zeros((1, 8), jnp.int32)
    variables = {"params": model.init(jax.random.key(0), prompt,
                                      train=False)["params"]}
    with pytest.raises(ValueError, match="max_len"):
        # 8 + 20 fits max_len=32, but + draft_len=7 of lookahead doesn't.
        generate_speculative(model, variables, prompt, 20)
    with pytest.raises(ValueError, match="draft_len"):
        generate_speculative(model, variables, prompt, 4, draft_len=0)
    with pytest.raises(ValueError, match="ngram"):
        generate_speculative(model, variables, prompt, 4, ngram=0)
    with pytest.raises(ValueError, match="non-empty"):
        generate_speculative(model, variables, prompt[:, :0], 4)


def test_speculative_rejects_ring_cache():
    """SWA models with a real ring cache can't rewind — must refuse."""
    model = tiny_llama(vocab_size=16, max_len=512, sliding_window=8)
    prompt = jnp.zeros((1, 4), jnp.int32)
    variables = {"params": model.init(jax.random.key(0), prompt,
                                      train=False)["params"]}
    with pytest.raises(NotImplementedError, match="ring cache"):
        generate_speculative(model, variables, prompt, 16)


def test_speculative_swa_full_cache_ok():
    """A sliding window that rounds up past max_len keeps the full cache
    — eligible, and still exact vs generate()."""
    model = tiny_llama(vocab_size=16, max_len=96, sliding_window=90)
    prompt = _repetitive_prompt(1, 10, 16)
    variables = {"params": model.init(jax.random.key(0), prompt,
                                      train=False)["params"]}
    ref = generate(model, variables, prompt, max_new_tokens=24)
    out = generate_speculative(model, variables, prompt, 24)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_speculative_tensor_parallel_matches_single_device(mesh4x2):
    """Speculation x TP: the sharded verify forward (Megatron weights +
    head-sharded cache, all-reduces on the mesh) must reproduce the
    single-device speculative output — which is itself bit-equal to
    greedy. Drafting/acceptance run on replicated tokens, so the only
    thing TP can break is the logits, and this catches that."""
    from pddl_tpu.parallel.tensor_parallel import TensorParallelStrategy

    model = tiny_gpt(vocab_size=16, max_len=96)
    variables = {"params": model.init(jax.random.key(0),
                                      jnp.zeros((1, 4), jnp.int32),
                                      train=False)["params"]}
    prompt = _repetitive_prompt(1, 12, 16)

    ref = generate(model, variables, prompt, max_new_tokens=24)
    strategy = TensorParallelStrategy(model_parallel=2)
    strategy._mesh = mesh4x2
    out, stats = generate_speculative(model, variables, prompt, 24,
                                      strategy=strategy,
                                      return_stats=True)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    assert stats["emitted"] == 24 and stats["ticks"] >= 1

    # int8 stays unsharded-only, loudly.
    from pddl_tpu.ops.quant import dequantize, quantize_int8

    with pytest.raises(NotImplementedError, match="unsharded"):
        generate_speculative(
            model, {"params": quantize_int8(variables["params"],
                                            min_elems=128)},
            prompt, 8, strategy=strategy, param_transform=dequantize)


def test_speculative_sampling_support_and_determinism():
    """Sampling mode: every emitted token must lie in the SUPPORT of the
    filtered conditional at its position (recomputed exactly from the
    full forward) — with top_k=2 that is a sharp check — and the draw
    must be a pure function of the rng key."""
    from pddl_tpu.models.gpt import filtered_logits

    model = tiny_gpt(vocab_size=16, max_len=96)
    prompt = _repetitive_prompt(2, 10, 16)
    variables = {"params": model.init(jax.random.key(0), prompt,
                                      train=False)["params"]}
    out1 = generate_speculative(model, variables, prompt, 30,
                                temperature=0.9, top_k=2,
                                rng=jax.random.key(7))
    out2 = generate_speculative(model, variables, prompt, 30,
                                temperature=0.9, top_k=2,
                                rng=jax.random.key(7))
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    out3 = generate_speculative(model, variables, prompt, 30,
                                temperature=0.9, top_k=2,
                                rng=jax.random.key(8))
    assert not np.array_equal(np.asarray(out1), np.asarray(out3))

    # Support check: token t+1 must have nonzero filtered probability
    # under the model's own conditional at position t.
    logits = model.apply(variables, out1[:, :-1], train=False)
    flog = filtered_logits(logits, temperature=0.9, top_k=2)
    p = prompt.shape[1]
    sel = np.take_along_axis(np.asarray(flog),
                             np.asarray(out1)[:, 1:, None], axis=-1)[..., 0]
    assert np.all(np.isfinite(sel[:, p - 1:])), "token outside top-k support"


def test_speculative_sampling_matches_plain_distribution():
    """Unbiasedness, empirically: on a near-uniform random model the
    unigram frequencies of speculative sampling must match plain
    generate() sampling within sampling noise (fixed seeds, ~1.6k draws
    each; the speculative path mixes accepted drafts, residual draws,
    and bonus draws, so a bias in ANY branch shows up here)."""
    model = tiny_gpt(vocab_size=8, max_len=128)
    prompt = _repetitive_prompt(8, 10, 8)
    variables = {"params": model.init(jax.random.key(1), prompt,
                                      train=False)["params"]}
    n_new = 100
    spec = generate_speculative(model, variables, prompt, n_new,
                                temperature=1.0, rng=jax.random.key(2))
    plain = generate(model, variables, prompt, n_new,
                     temperature=1.0, rng=jax.random.key(3))
    p = prompt.shape[1]
    f_spec = np.bincount(np.asarray(spec)[:, p:].ravel(), minlength=8)
    f_plain = np.bincount(np.asarray(plain)[:, p:].ravel(), minlength=8)
    n = f_spec.sum()
    # Each frequency ~ Binomial(n, q): compare both against each other
    # with a 5-sigma-ish band on the difference of proportions.
    diff = np.abs(f_spec - f_plain) / n
    sigma = np.sqrt(2 * (f_plain / n) * (1 - f_plain / n) / n)
    assert np.all(diff < 5 * sigma + 0.01), (f_spec, f_plain)


def test_speculative_sampling_validation():
    model = tiny_gpt(vocab_size=16, max_len=64)
    prompt = jnp.zeros((1, 4), jnp.int32)
    variables = {"params": model.init(jax.random.key(0), prompt,
                                      train=False)["params"]}
    with pytest.raises(ValueError, match="rng"):
        generate_speculative(model, variables, prompt, 8, temperature=0.8)
    with pytest.raises(ValueError, match="top_k/top_p"):
        generate_speculative(model, variables, prompt, 8, top_k=4)


def test_speculative_sampling_tensor_parallel(mesh4x2):
    """Sampling x TP: runs sharded, same key -> same tokens as the
    unsharded sampling path (identical logits, identical coins)."""
    from pddl_tpu.parallel.tensor_parallel import TensorParallelStrategy

    model = tiny_gpt(vocab_size=16, max_len=96)
    prompt = _repetitive_prompt(1, 10, 16)
    variables = {"params": model.init(jax.random.key(0), prompt,
                                      train=False)["params"]}
    ref = generate_speculative(model, variables, prompt, 20,
                               temperature=0.8, top_k=4,
                               rng=jax.random.key(5))
    strategy = TensorParallelStrategy(model_parallel=2)
    strategy._mesh = mesh4x2
    out = generate_speculative(model, variables, prompt, 20,
                               temperature=0.8, top_k=4,
                               rng=jax.random.key(5), strategy=strategy)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_cache_position_counters_are_exactly_the_scalar_int32_leaves():
    """The loud-failure registry for `_rewind_index` (and the serving
    engine's slot machinery): position counters are matched BY NAME
    (gpt.CACHE_INDEX_KEYS). Enumerate every scalar int32 leaf of each
    family's decode cache and require the name registry to cover it —
    a future scalar int32 cache leaf that is NOT a position counter
    fails here and forces an explicit decision in both consumers."""
    from pddl_tpu.models.gpt import CACHE_INDEX_KEYS, is_cache_index_path
    from pddl_tpu.models.speculative import _rewind_index

    for factory in (tiny_gpt, tiny_llama):
        model = factory(vocab_size=16, max_len=64)
        dec = model.clone(decode=True)
        dummy = jnp.zeros((1, 1), jnp.int32)
        cache = jax.eval_shape(
            lambda d=dec: d.init(jax.random.key(0), dummy, train=False)
        )["cache"]
        leaves = jax.tree_util.tree_leaves_with_path(cache)
        scalar_int32 = [(path, leaf) for path, leaf in leaves
                        if leaf.ndim == 0 and leaf.dtype == jnp.int32]
        assert scalar_int32, "decode cache lost its position counters?"
        for path, _ in scalar_int32:
            name = str(getattr(path[-1], "key", path[-1]))
            assert is_cache_index_path(path), (
                f"scalar int32 cache leaf {name!r} is not a registered "
                f"position counter {sorted(CACHE_INDEX_KEYS)}: teach "
                "_rewind_index/the serving engine about it explicitly")
        # And the name match must hit every counter: rewinding a real
        # cache rewrites exactly the registered leaves.
        real = jax.tree.map(lambda sd: jnp.zeros(sd.shape, sd.dtype), cache)
        wound = _rewind_index(real, jnp.int32(7))
        for path, leaf in jax.tree_util.tree_leaves_with_path(wound):
            if is_cache_index_path(path):
                assert int(leaf) == 7
            else:
                assert leaf.shape != ()  # K/V tensors untouched by name
