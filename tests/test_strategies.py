"""Strategy semantics: batch arithmetic, state placement, PS sharding."""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from pddl_tpu.data.synthetic import SyntheticImageClassification
from pddl_tpu.models.resnet import tiny_resnet
from pddl_tpu.parallel import (
    MirroredStrategy,
    MultiWorkerMirroredStrategy,
    ParameterServerStrategy,
    SingleDeviceStrategy,
    get_strategy,
)
from pddl_tpu.train.loop import Trainer


def _ds(batch):
    return SyntheticImageClassification(
        batch_size=batch, image_size=32, num_classes=10, signal_strength=3.0
    )


def test_registry_lookup():
    assert isinstance(get_strategy("single"), SingleDeviceStrategy)
    assert isinstance(get_strategy("mirrored"), MirroredStrategy)
    assert isinstance(get_strategy("multiworker"), MultiWorkerMirroredStrategy)
    assert isinstance(get_strategy("ps"), ParameterServerStrategy)


def test_single_device_one_replica():
    s = SingleDeviceStrategy()
    assert s.num_replicas_in_sync == 1
    assert s.scale_batch_size(32) == 32


def test_mirrored_batch_arithmetic():
    s = MirroredStrategy()
    # the reference's global batch 32*num_replicas (imagenet-resnet50-mirror.py:54)
    assert s.scale_batch_size(32) == 32 * 8


def test_multiworker_single_process_fallback():
    """With one process the multiworker strategy degrades to mirrored over
    all devices (no jax.distributed needed) — same property as running the
    reference's multiworker script with SLURM_NTASKS=1."""
    s = MultiWorkerMirroredStrategy()
    s.setup()
    assert s.num_workers == 1
    assert s.num_replicas_in_sync == 8


def test_ps_shards_large_params_only():
    strat = ParameterServerStrategy(min_shard_bytes=1 << 10)
    tr = Trainer(tiny_resnet(num_classes=10, width_multiplier=1.0),
                 strategy=strat, learning_rate=1e-2)
    tr.fit(_ds(32), epochs=1, steps_per_epoch=2, verbose=0)
    params = tr.state.params
    # Head kernel (features, 10): features dim small; stem conv tiny ->
    # replicated. Find at least one sharded leaf and one replicated leaf.
    specs = [leaf.sharding.spec for leaf in jax.tree.leaves(params)]
    assert any(spec != P() for spec in specs), "expected some sharded params"
    assert any(spec == P() for spec in specs), "expected some replicated params"
    # Optimizer moments follow the same layout (ZeRO-style).
    opt_specs = [leaf.sharding.spec for leaf in jax.tree.leaves(tr.state.opt_state)
                 if hasattr(leaf, "sharding")]
    assert any(spec != P() for spec in opt_specs)


def test_ps_training_matches_replicated_numerics():
    """Sharded-state SPMD must be numerically equivalent to replicated DP —
    the observable the reference's PS mode cannot even guarantee (async).

    Two regimes: Adam is compared after ONE step only — its m/sqrt(v)
    update approaches sign(grad) at step 2, so the float-reassociation
    noise that different GSPMD layouts legally introduce (~1e-8) flips
    near-zero gradient signs and diverges chaotically, which is a property
    of Adam, not of the sharding. SGD's smooth update composes those
    reassociation differences linearly, so three steps stay tight."""
    for optimizer, steps, rtol, atol in (("adam", 1, 1e-5, 1e-7),
                                         ("sgd", 3, 5e-4, 5e-6)):
        ds = _ds(32)
        t_dp = Trainer(tiny_resnet(num_classes=10), optimizer=optimizer,
                       learning_rate=1e-2, strategy=MirroredStrategy(),
                       seed=11)
        t_ps = Trainer(tiny_resnet(num_classes=10), optimizer=optimizer,
                       learning_rate=1e-2,
                       strategy=ParameterServerStrategy(min_shard_bytes=1 << 10),
                       seed=11)
        h_dp = t_dp.fit(ds, epochs=1, steps_per_epoch=steps, verbose=0)
        h_ps = t_ps.fit(ds, epochs=1, steps_per_epoch=steps, verbose=0)
        np.testing.assert_allclose(h_dp.history["loss"][0],
                                   h_ps.history["loss"][0], rtol=2e-4)
        for a, b in zip(jax.tree.leaves(t_dp.state.params),
                        jax.tree.leaves(t_ps.state.params)):
            np.testing.assert_allclose(jax.device_get(a), jax.device_get(b),
                                       rtol=rtol, atol=atol,
                                       err_msg=f"optimizer={optimizer}")


def test_ps_num_ps_caps_sharding():
    """num_ps caps the shard count like max_shards=NUM_PS
    (imagenet-resnet50-ps.py:78): with num_ps=2 on an 8-device axis,
    shardable leaves split exactly 2 ways (sub-axis layout), never more."""
    strat = ParameterServerStrategy(min_shard_bytes=1, num_ps=2)
    tr = Trainer(tiny_resnet(num_classes=10), strategy=strat, learning_rate=1e-2)
    tr.fit(_ds(32), epochs=1, steps_per_epoch=1, verbose=0)
    leaves = jax.tree.leaves(tr.state.params)
    # Nothing exceeds the cap: no full-axis ("data") placements at all.
    assert all(
        all(ax != "data" for ax in jax.tree.leaves(tuple(leaf.sharding.spec)))
        for leaf in leaves
    )
    # And the cap is used, not collapsed to replication: 2-way splits exist.
    two_way = [
        leaf for leaf in leaves
        if not leaf.sharding.is_fully_replicated
        and "_data_shard" in leaf.sharding.mesh.axis_names
    ]
    assert two_way
    for leaf in two_way:
        assert len(leaf.sharding.device_set) == 8  # still spans all devices
        shapes = {s.data.shape for s in leaf.addressable_shards}
        assert len(shapes) == 1  # even 2-way split, 4-way replicated


def test_distribute_batch_global_shape(mesh8):
    s = MirroredStrategy()
    batch = {"image": np.zeros((32, 8, 8, 3), np.float32),
             "label": np.zeros((32,), np.int32)}
    out = s.distribute_batch(batch)
    assert out["image"].shape == (32, 8, 8, 3)
    assert out["image"].sharding.spec == P("data")
    # each device holds 4 samples
    assert out["image"].addressable_shards[0].data.shape == (4, 8, 8, 3)


def test_weight_decay_unsupported_optimizer_raises():
    from pddl_tpu.train.state import make_optimizer

    with pytest.raises(ValueError, match="weight_decay"):
        make_optimizer("adam", 1e-3, weight_decay=1e-4)
    make_optimizer("adamw", 1e-3, weight_decay=1e-4)  # supported: no raise


def test_scale_learning_rate_linear_rule():
    strat = MirroredStrategy()
    # Horovod's 0.1 * size rule (imagenet-resnet50-hvd.py:99).
    assert strat.scale_learning_rate(0.1) == pytest.approx(0.1 * 8)
