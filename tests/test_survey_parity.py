"""Executable SURVEY.md §2 parity manifest.

One assertion per reference component/constant, with the reference
citation inline — so "does the framework cover SURVEY's inventory?" is a
test run, not a reading exercise. Structural checks only (surfaces,
registry names, reference-exact defaults); behavior is covered by the
per-component test files each assertion names.
"""

import numpy as np
import pytest


# --------------------------------------------------------- §2a components
def test_c1_to_c6_strategy_presets_cover_all_eight_scripts():
    """C1-C6: every reference script has a named preset (SURVEY §2a)."""
    from pddl_tpu.config import PRESETS

    assert set(PRESETS) == {
        "single", "single-pretrained",                 # imagenet-resnet50[-pretrained].py
        "mirrored", "mirrored-pretrained",             # -mirror variants
        "multiworker", "multiworker-pretrained",       # -multiworkers variants
        "hvd",                                         # -hvd.py
        "ps",                                          # -ps.py
    }


def test_strategy_registry_names():
    from pddl_tpu.parallel.base import _STRATEGIES

    for name in ("single", "mirrored", "multiworker", "ps",
                 "tensor_parallel", "expert_parallel", "pipeline"):
        assert name in _STRATEGIES, name


def test_reference_batch_arithmetic():
    """32 x replicas (mirror.py:54); 128/256 x n (multiworkers.py:70-72)."""
    from pddl_tpu.config import PRESETS

    assert PRESETS["single"].per_replica_batch == 32
    assert PRESETS["mirrored"].per_replica_batch == 32
    assert PRESETS["multiworker"].per_replica_batch == 128
    assert PRESETS["multiworker"].val_per_replica_batch == 256
    assert PRESETS["multiworker-pretrained"].per_replica_batch == 32


def test_hvd_preset_reproduces_script_observables():
    """LR 0.1 x size + 3-epoch warmup + post-batch shard + crop 160
    (imagenet-resnet50-hvd.py:77-81,89,99,114)."""
    from pddl_tpu.config import PRESETS

    hvd = PRESETS["hvd"]
    assert hvd.learning_rate == pytest.approx(0.1)
    assert hvd.scale_lr and hvd.warmup_epochs == 3
    assert hvd.data_shard == "batch" and hvd.crop == 160


def test_pretrained_presets_freeze_bn():
    """base_model(training=False) (imagenet-pretrained-resnet50.py:57)."""
    from pddl_tpu.config import PRESETS

    for name in ("single-pretrained", "mirrored-pretrained",
                 "multiworker-pretrained"):
        assert PRESETS[name].bn_mode == "frozen", name


def test_c9_model_zoo_and_keras_parity_surface():
    """C9: ResNet-50 exact-arch parity + .h5 import (the weights='imagenet'
    mode, imagenet-pretrained-resnet50.py:56); behavior in
    test_keras_parity.py / test_checkpoint.py."""
    from pddl_tpu.ckpt import load_keras_resnet50_h5  # noqa: F401
    from pddl_tpu.ckpt.keras_import import export_keras_style_h5  # noqa: F401
    from pddl_tpu.models.registry import list_models

    models = set(list_models())
    assert {"resnet18", "resnet34", "resnet50", "resnet101",
            "resnet152"} <= models
    # Beyond-parity families present too.
    assert {"vit_s16", "vit_b16", "vit_l16", "gpt_small"} <= models


def test_c10_callbacks_reference_defaults():
    """ReduceLROnPlateau(0.1, patience 5, min_lr 1e-5) + EarlyStopping
    (min_delta 1e-3, patience 10) on val_loss (imagenet-resnet50.py:64-65)."""
    from pddl_tpu.train.callbacks import EarlyStopping, ReduceLROnPlateau

    r = ReduceLROnPlateau()
    assert (r.monitor, r.factor, r.patience, r.min_lr) == \
        ("val_loss", 0.1, 5, 1e-5)
    e = EarlyStopping()
    assert (e.monitor, e.min_delta, e.patience) == ("val_loss", 0.001, 10)


# ------------------------------------------------ §2b native substrate map
def test_c13_hvd_shim_surface():
    """C13: the Horovod symbols the reference script calls
    (imagenet-resnet50-hvd.py:16,28,41,99,101,111-115)."""
    from pddl_tpu.compat import hvd

    for sym in ("init", "rank", "size", "local_rank", "allreduce",
                "allgather", "broadcast", "DistributedOptimizer"):
        assert callable(getattr(hvd, sym)), sym
    for cb in ("BroadcastGlobalVariablesCallback", "MetricAverageCallback",
               "LearningRateWarmupCallback"):
        assert hasattr(hvd.callbacks, cb), cb


def test_c14_min_size_partitioner_reference_default():
    """256 KiB min shard, the reference's value
    (imagenet-resnet50-ps.py:75-78)."""
    from pddl_tpu.core.sharding import MinSizePartitioner

    assert MinSizePartitioner().min_shard_bytes == 256 * 1024


def test_c15_native_runtime_symbols():
    """C15: own C++ loader + TFRecord record layer (tf.data analogue)."""
    from conftest import native_build_error

    err = native_build_error(tfrecord=True)
    if err:
        pytest.skip(f"native library unbuildable: {err}")
    from pddl_tpu.data.native_loader import _load_lib

    lib = _load_lib()
    for sym in ("pddl_loader_open", "pddl_loader_next", "pddl_tfr_open",
                "pddl_tfr_next", "pddl_crc32c"):
        assert hasattr(lib, sym), sym


def test_c16_kernels_and_collectives_surface():
    """C16 + C11/C12: Pallas kernels and named-axis collectives."""
    from pddl_tpu.core import collectives
    from pddl_tpu.ops.attention import attention_reference, flash_attention  # noqa: F401
    from pddl_tpu.ops.ring_attention import ring_attention  # noqa: F401

    for sym in ("psum", "pmean", "broadcast", "all_gather",
                "reduce_scatter", "ppermute_ring"):
        assert callable(getattr(collectives, sym, None)), sym


# ----------------------------------------------- §2c parallelism checklist
def test_parallelism_checklist_importable():
    """Every §2c row (incl. beyond-parity TP/SP/EP/PP) has a surface."""
    from pddl_tpu.models.gpipe import GPipeModel  # noqa: F401  (PP)
    from pddl_tpu.ops.moe import SwitchFFN  # noqa: F401  (EP)
    from pddl_tpu.ops.pipeline import gpipe_apply  # noqa: F401
    from pddl_tpu.ops.ring_attention import sequence_parallel_attention  # noqa: F401  (SP)
    from pddl_tpu.parallel import (  # noqa: F401
        MirroredStrategy,                 # DP single host
        MultiWorkerMirroredStrategy,      # DP multi host
        ParameterServerStrategy,          # PS / ZeRO-style sharded state
        PipelineStrategy,                 # PP
        TensorParallelStrategy,           # TP
    )
    from pddl_tpu.parallel.tensor_parallel import ExpertParallelStrategy  # noqa: F401


def test_scaling_rules_are_linear():
    """scale_batch_size = b x replicas; scale_learning_rate = lr x size."""
    from pddl_tpu.parallel.mirrored import MirroredStrategy

    s = MirroredStrategy()
    s.setup()  # public path; conftest provides the 8 fake devices
    assert s.scale_batch_size(32) == 256
    assert np.isclose(s.scale_learning_rate(0.1), 0.8)


# --------------------------------------------------- round-2 (VERDICT r1)
def test_r2_reference_callback_parity_everywhere():
    """VERDICT r1 #3: no preset drops the reference's val_loss callback
    pair (imagenet-resnet50-hvd.py:106-107, -ps.py:139-140)."""
    from pddl_tpu.config import PRESETS

    for name, cfg in PRESETS.items():
        assert cfg.reduce_lr_on_plateau and cfg.early_stopping, name


def test_r2_weight_acquisition_surface():
    """VERDICT r1 #6: weights='imagenet' is runnable end to end — the
    pretrained presets carry it and the fetch helper documents URL+hash
    (imagenet-pretrained-resnet50.py:56)."""
    from pddl_tpu.ckpt import fetch_keras_resnet50_weights  # noqa: F401
    from pddl_tpu.ckpt.fetch import KERAS_RESNET_WEIGHTS
    from pddl_tpu.config import PRESETS

    for name in ("single-pretrained", "mirrored-pretrained",
                 "multiworker-pretrained"):
        assert PRESETS[name].weights == "imagenet", name
    fname, md5 = KERAS_RESNET_WEIGHTS["resnet50"]["notop"]
    assert fname.endswith(".h5") and len(md5) == 32


def test_r2_partitioner_middle_ground():
    """VERDICT r1 #5: intermediate shard counts (2..N-1) are realized, not
    collapsed to replication (imagenet-resnet50-ps.py:78 max_shards is a
    free count)."""
    from pddl_tpu.core.sharding import MinSizePartitioner

    part = MinSizePartitioner(min_shard_bytes=1, max_shards=2)
    assert part.feasible_shards((64, 64), np.float32, 8) == (2, 0)


def test_r2_stem_variant_and_transforms():
    """VERDICT r1 #4: the space-to-depth throughput stem exists with exact
    two-way kernel transforms (models/resnet.py)."""
    from pddl_tpu.models.resnet import (  # noqa: F401
        s2d_stem_kernel,
        s2d_stem_kernel_inverse,
    )
    from pddl_tpu.config import ExperimentConfig

    assert ExperimentConfig().stem == "keras"  # parity default untouched


def test_r2_convergence_artifacts_committed():
    """VERDICT r1 #2: real-data convergence curves are repo artifacts
    (docs/CONVERGENCE.md quotes them; examples/real_data_convergence.py
    regenerates them)."""
    import json
    import os

    root = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "artifacts", "convergence")
    for track in ("digits", "pycorpus"):
        path = os.path.join(root, f"{track}.jsonl")
        assert os.path.isfile(path), path
        with open(path) as f:
            header = json.loads(f.readline())
            rows = [json.loads(line) for line in f]
        assert header["config"]["seed"] == 0
        assert len(rows) >= 2
        assert rows[-1]["val_loss"] < rows[0]["val_loss"]  # it converged


# --------------------------------------------------- round-3 (VERDICT r2)
def _artifact(*parts):
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(root, "artifacts", *parts)


def test_r3_kernel_head_to_head_artifact():
    """VERDICT r2 weak #2/#5 closure: the flash kernel's efficiency is
    pinned against the JAX-shipped kernels on hardware — ours must beat
    both stock implementations in the committed record."""
    import json

    with open(_artifact("gpt_bench", "r03_kernel_head_to_head.json")) as f:
        rec = json.loads(f.read())
    ours = rec["ms"]["ours"]
    for stock in ("stock_flash", "splash"):
        assert rec["ms"][stock]["fwd"] > ours["fwd"], stock
        assert rec["ms"][stock]["fwd_bwd"] > ours["fwd_bwd"], stock


def test_r3_llama_family_complete():
    """Round-3 breadth: the modern-decoder lineage is a first-class
    family — registry names, HF import AND export, GQA/SWA/qkv-bias
    coverage, TP rule table."""
    from pddl_tpu.ckpt.hf_export import export_hf_llama  # noqa: F401
    from pddl_tpu.ckpt.hf_import import load_hf_llama  # noqa: F401
    from pddl_tpu.models import Llama, list_models
    from pddl_tpu.parallel.tensor_parallel import LLAMA_TP_RULES  # noqa: F401

    assert {"tiny_llama", "llama_1b"} <= set(list_models())
    for field in ("num_kv_heads", "sliding_window", "qkv_bias",
                  "rope_theta"):
        assert field in Llama.__dataclass_fields__, field


def test_r3_topk_moe_and_sliding_window_surfaces():
    """Round-3 ops: GShard/Mixtral top-2 routing and Mistral SWA exist on
    their public surfaces (defaults preserve round-2 behavior)."""
    import inspect

    from pddl_tpu.ops.attention import flash_attention
    from pddl_tpu.ops.moe import SwitchFFN

    assert SwitchFFN.__dataclass_fields__["top_k"].default == 1
    assert "window" in inspect.signature(flash_attention).parameters


def test_r3_llama_bench_artifact():
    """The new family's on-chip throughput is pinned like the GPT
    shape's (benchmarks/gpt_train_bench.py --family llama)."""
    import json

    with open(_artifact("gpt_bench", "r03_llama_b8_s2048.json")) as f:
        rec = json.loads(f.read())
    assert rec["config"]["family"] == "llama"
    assert rec["value"] > 90_000  # tokens/sec/chip at B8 S2048
