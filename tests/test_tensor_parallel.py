"""Tensor parallelism: Megatron-style weight sharding over the ``model``
axis (beyond-parity capability; the mesh reserves the axis — SURVEY.md §2c).

Checks on the fake 8-device mesh: rule table places shards on the right
dims, optimizer moments inherit the layout, TP training is numerically the
sync-SPMD identity (same global batch + seed => same params as
single-device), and DP x TP composes.
"""

import jax
import numpy as np
from jax.sharding import PartitionSpec as P

from pddl_tpu.core.mesh import MODEL_AXIS
from pddl_tpu.data.synthetic import SyntheticImageClassification
from pddl_tpu.models.vit import tiny_vit
from pddl_tpu.parallel import SingleDeviceStrategy, TensorParallelStrategy
from pddl_tpu.train.loop import Trainer


def _dataset(batch, **kw):
    kw.setdefault("image_size", 32)
    kw.setdefault("num_classes", 8)
    kw.setdefault("signal_strength", 3.0)
    return SyntheticImageClassification(batch_size=batch, **kw)


def _fit(strategy, batch=16, seed=3, steps=4, optimizer="adamw", lr=1e-2,
         epochs=1):
    tr = Trainer(tiny_vit(num_classes=8, num_heads=4), optimizer=optimizer,
                 learning_rate=lr, strategy=strategy, seed=seed)
    hist = tr.fit(_dataset(batch, seed=7), epochs=epochs,
                  steps_per_epoch=steps, verbose=0)
    return tr, hist


def test_tp_param_shardings_follow_megatron_layout():
    strategy = TensorParallelStrategy(model_parallel=4)
    tr, _ = _fit(strategy)
    params = tr.state.params

    def spec_of(leaf):
        return leaf.sharding.spec

    blk = params["block0"]
    # (specs are canonicalized: trailing Nones trimmed)
    # column-parallel: q/k/v kernels (E, H, D) sharded on H
    assert spec_of(blk["attn"]["query"]["kernel"]) == P(None, MODEL_AXIS)
    assert spec_of(blk["attn"]["query"]["bias"]) == P(MODEL_AXIS)
    # row-parallel: out kernel (E, E) sharded on the (head-major) input dim
    assert spec_of(blk["attn"]["out"]["kernel"]) == P(MODEL_AXIS)
    assert spec_of(blk["attn"]["out"]["bias"]) == P()
    # MLP: up column-parallel, down row-parallel
    assert spec_of(blk["mlp1"]["kernel"]) == P(None, MODEL_AXIS)
    assert spec_of(blk["mlp1"]["bias"]) == P(MODEL_AXIS)
    assert spec_of(blk["mlp2"]["kernel"]) == P(MODEL_AXIS)
    assert spec_of(blk["mlp2"]["bias"]) == P()
    # Non-transformer leaves stay replicated
    assert spec_of(params["patch_embed"]["kernel"]) == P()


def test_tp_optimizer_state_inherits_layout():
    strategy = TensorParallelStrategy(model_parallel=4)
    tr, _ = _fit(strategy)
    # Find an adamw moment leaf for mlp1/kernel and check it is sharded.
    flat = jax.tree_util.tree_flatten_with_path(tr.state.opt_state)[0]
    hits = [leaf for path, leaf in flat
            if "mlp1" in str(path) and "kernel" in str(path)
            and hasattr(leaf, "sharding") and leaf.ndim == 2]
    assert hits, "no mlp1 kernel moments found in opt_state"
    assert all(h.sharding.spec == P(None, MODEL_AXIS) for h in hits)


def test_tp_matches_single_device_numerics():
    """Sharding the weights must not change the math (sync-SPMD identity).

    SGD, not adamw: TP splits contractions into partial sums whose float
    rounding differs from the unsharded order, and adaptive optimizers
    amplify that noise through grad/sqrt(v) for near-zero grads. With SGD
    the param delta is linear in the grad, so agreement is tight.
    """
    # model_parallel=4 divides num_heads=4, so q/k/v genuinely shard by
    # head here (8 would trip the divisibility fallback and silently test
    # replicated attention weights).
    tp, _ = _fit(TensorParallelStrategy(model_parallel=4), batch=16,
                 optimizer="sgd", steps=3)
    single, _ = _fit(SingleDeviceStrategy(), batch=16,
                     optimizer="sgd", steps=3)
    a = jax.device_get(tp.state.params)
    b = jax.device_get(single.state.params)
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   atol=1e-4, rtol=1e-3)


def test_dp_tp_composes_and_trains():
    strategy = TensorParallelStrategy(model_parallel=2)  # data=4 x model=2
    assert strategy.num_replicas_in_sync == 4
    tr, hist = _fit(strategy, batch=strategy.scale_batch_size(4), steps=4,
                    epochs=2, lr=1e-3)
    assert hist.history["loss"][-1] < hist.history["loss"][0]


def test_vocab_parallel_embed_and_head(mesh4x2):
    """GPT under TP shards token_embed [V,E] and lm_head [E,V] over
    `model` (Megatron vocab parallelism) and still trains/decodes
    exactly."""
    import jax.numpy as jnp

    from pddl_tpu.data.synthetic import SyntheticLanguageModeling
    from pddl_tpu.models.gpt import generate, tiny_gpt
    from pddl_tpu.train.loop import Trainer

    strategy = TensorParallelStrategy(model_parallel=2)
    strategy._mesh = mesh4x2
    ds = SyntheticLanguageModeling(batch_size=16, seq_len=16, vocab_size=16,
                                   seed=0)
    model = tiny_gpt(vocab_size=16, max_len=32)
    tr = Trainer(model, optimizer="adamw", learning_rate=3e-3,
                 strategy=strategy, seed=0,
                 input_key="tokens", target_key="targets")
    tr.fit(ds, epochs=1, steps_per_epoch=4, verbose=0)

    embed = tr.state.params["token_embed"]["embedding"]
    head = tr.state.params["lm_head"]["kernel"]
    bias = tr.state.params["lm_head"]["bias"]
    assert embed.sharding.spec[0] == MODEL_AXIS, embed.sharding
    assert head.sharding.spec == (None, MODEL_AXIS), head.sharding
    assert bias.sharding.spec == (MODEL_AXIS,), bias.sharding

    # Sharded decoding still matches the single-device path bit for bit.
    variables = {"params": jax.device_get(tr.state.params)}
    prompt = jnp.asarray(ds.batch(0)["tokens"][:2, :4])
    ref = generate(model, variables, prompt, max_new_tokens=4)
    out = generate(model, variables, prompt, max_new_tokens=4,
                   strategy=strategy)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


def test_vocab_padding_enables_tp_on_indivisible_vocab(mesh4x2):
    """Real vocabs divide nothing (GPT-2's 50257); vocab_multiple pads the
    embed/head rows so vocab parallelism engages, while sliced logits keep
    the model function identical to the unpadded head."""
    import jax.numpy as jnp

    from pddl_tpu.models.gpt import tiny_gpt

    vocab = 30  # indivisible by the model axis (2)
    model = tiny_gpt(vocab_size=vocab, max_len=32, vocab_multiple=8)
    tokens = jnp.arange(2 * 8, dtype=jnp.int32).reshape(2, 8) % vocab
    variables = model.init(jax.random.key(0), tokens, train=False)
    assert variables["params"]["token_embed"]["embedding"].shape[0] == 32
    assert variables["params"]["lm_head"]["kernel"].shape[1] == 32
    logits = model.apply(variables, tokens, train=False)
    assert logits.shape[-1] == vocab  # padding sliced away

    strategy = TensorParallelStrategy(model_parallel=2)
    strategy._mesh = mesh4x2
    sh = strategy.tree_sharding(variables["params"])
    assert sh["token_embed"]["embedding"].spec[0] == MODEL_AXIS
    assert sh["lm_head"]["kernel"].spec == (None, MODEL_AXIS)

    # And the padded model trains + decodes under TP.
    from pddl_tpu.data.synthetic import SyntheticLanguageModeling
    from pddl_tpu.models.gpt import generate
    from pddl_tpu.train.loop import Trainer

    ds = SyntheticLanguageModeling(batch_size=16, seq_len=16,
                                   vocab_size=vocab, seed=0)
    tr = Trainer(model, optimizer="adamw", learning_rate=3e-3,
                 strategy=strategy, seed=0,
                 input_key="tokens", target_key="targets")
    tr.fit(ds, epochs=1, steps_per_epoch=4, verbose=0)
    out = generate(model, {"params": jax.device_get(tr.state.params)},
                   tokens[:, :4], max_new_tokens=4, strategy=strategy)
    assert (np.asarray(out) < vocab).all()  # padded ids never sampled
