"""Token-corpus pipeline (the LM analogue of the ImageNet ingest):
byte-level preparation, memmap window batching, determinism, sharding,
and the CLI path training a GPT on a real corpus directory."""

import json
import os

import numpy as np
import pytest

from pddl_tpu.data.text import (
    TokenFileDataset,
    encode_text_file,
    load_token_corpus,
    read_meta,
)


def _corpus(tmp_path, text=None, split="train"):
    text = text or ("hello tpu world. " * 200)
    txt = tmp_path / f"{split}.txt"
    txt.write_text(text)
    return str(tmp_path)


def test_encode_text_file_byte_level(tmp_path):
    d = _corpus(tmp_path, text="abc")
    n, vocab = encode_text_file(os.path.join(d, "train.txt"),
                                os.path.join(d, "train.bin"))
    assert (n, vocab) == (3, 256)
    toks = np.fromfile(os.path.join(d, "train.bin"), dtype="<u2")
    assert toks.tolist() == [ord("a"), ord("b"), ord("c")]
    assert read_meta(d)["vocab_size"] == 256


def test_token_dataset_shapes_and_shift(tmp_path):
    d = _corpus(tmp_path)
    train, _ = load_token_corpus(d, seq_len=16, train_batch_size=4,
                                 val_batch_size=4)
    batch = next(iter(train))
    assert batch["tokens"].shape == (4, 16)
    assert batch["targets"].shape == (4, 16)
    # Next-token shift within every window.
    np.testing.assert_array_equal(batch["tokens"][:, 1:],
                                  batch["targets"][:, :-1])


def test_determinism(tmp_path):
    d = _corpus(tmp_path)
    encode_text_file(os.path.join(d, "train.txt"),
                     os.path.join(d, "train.bin"))
    path = os.path.join(d, "train.bin")
    a = TokenFileDataset(path, batch_size=2, seq_len=8, seed=5)
    b = TokenFileDataset(path, batch_size=2, seq_len=8, seed=5)
    ea = [x["tokens"] for x in a]
    eb = [x["tokens"] for x in b]
    assert all((x == y).all() for x, y in zip(ea, eb))
    # Second epoch reshuffles the window order.
    ea2 = [x["tokens"] for x in a]
    assert not all((x == y).all() for x, y in zip(ea, ea2))
    assert len(ea) == a.batches_per_epoch


def test_sharding_partitions_windows(tmp_path):
    d = _corpus(tmp_path)
    encode_text_file(os.path.join(d, "train.txt"),
                     os.path.join(d, "train.bin"))
    path = os.path.join(d, "train.bin")
    toks = np.fromfile(path, dtype="<u2").astype(np.int32)
    shards = [
        TokenFileDataset(path, batch_size=4, seq_len=8, shuffle=False,
                         process_index=i, process_count=2)
        for i in range(2)
    ]
    for proc, s in enumerate(shards):
        rows = [row for batch in s for row in batch["tokens"]]
        # Unshuffled shard p yields windows p, p+2, p+4, ... in order.
        for j, row in enumerate(rows):
            w = proc + 2 * j
            np.testing.assert_array_equal(row, toks[w * 8:w * 8 + 8])
    # Each shard yields its local share of the global batch.
    first = next(iter(shards[0]))
    assert first["tokens"].shape == (2, 8)


def test_shards_yield_equal_batch_counts(tmp_path):
    """SPMD safety: every process must see the same steps per epoch."""
    # 101 windows over 2 processes would give 51/50 without the global
    # floor — and a deadlocked collective on a real pod.
    toks = np.arange(101 * 8 + 1, dtype="<u2") % 250
    path = str(tmp_path / "train.bin")
    toks.tofile(path)
    shards = [
        TokenFileDataset(path, batch_size=2, seq_len=8, shuffle=False,
                         process_index=i, process_count=2)
        for i in range(2)
    ]
    counts = [sum(1 for _ in s) for s in shards]
    assert counts[0] == counts[1] == shards[0].batches_per_epoch == 50


def test_binonly_corpus_vocab_guard(tmp_path):
    """A .bin without meta.json is bounded by scanning its token ids."""
    from pddl_tpu.config import get_preset
    from pddl_tpu.run import build_data, build_trainer

    toks = (np.arange(600, dtype="<u2") % 500)  # ids up to 499
    toks.tofile(str(tmp_path / "train.bin"))
    cfg = get_preset("single").replace(
        model="tiny_gpt", data_dir=str(tmp_path), num_classes=256,
        seq_len=8, per_replica_batch=2,
    )
    trainer, _ = build_trainer(cfg)
    with pytest.raises(ValueError, match="vocab size 500"):
        build_data(cfg, trainer.strategy)


def test_vocab_mismatch_rejected(tmp_path):
    d = _corpus(tmp_path)
    from pddl_tpu.config import get_preset
    from pddl_tpu.run import build_data, build_trainer

    cfg = get_preset("single").replace(
        model="tiny_gpt", data_dir=d, num_classes=8, seq_len=8,
        per_replica_batch=2,
    )
    trainer, _ = build_trainer(cfg)
    # First run from a raw train.txt: preparation happens during
    # build_data, and the guard must still fire (byte vocab 256 > 8).
    with pytest.raises(ValueError, match="vocab"):
        build_data(cfg, trainer.strategy)


def test_refuses_mixing_token_spaces(tmp_path):
    d = _corpus(tmp_path, split="val")
    # Externally tokenized corpus: meta records a non-byte vocab.
    with open(os.path.join(d, "meta.json"), "w") as f:
        json.dump({"vocab_size": 50257, "vocab": "bpe"}, f)
    np.zeros(100, dtype="<u2").tofile(os.path.join(d, "train.bin"))
    with pytest.raises(ValueError, match="refusing to byte-encode"):
        load_token_corpus(d, seq_len=8, train_batch_size=2,
                          val_batch_size=2)


def test_cli_trains_gpt_on_corpus(tmp_path):
    d = _corpus(tmp_path)
    from pddl_tpu.run import main

    rc = main([
        "--preset", "single", "--model", "tiny_gpt", "--data-dir", d,
        "--num-classes", "256", "--batch", "4", "--epochs", "1",
        "--steps-per-epoch", "2", "--verbose", "0",
    ])
    assert rc == 0
