"""TFRecord layer tests: CRC parity (Python vs native vs TF), framing
round-trips, TF interop both directions, sharding, shuffle determinism,
and corruption detection (the record-level slice of tf.data's C++ runtime,
SURVEY.md §2b C15 — /root/reference/imagenet-resnet50.py:20-34)."""

import struct

import pytest

from pddl_tpu.data.tfrecord import (
    TFRecordReader,
    crc32c,
    masked_crc32c,
    open_tfrecords,
    read_tfrecord,
    write_tfrecord,
)
from conftest import native_build_error

_BUILD_ERROR = native_build_error(tfrecord=True)
pytestmark = pytest.mark.skipif(
    bool(_BUILD_ERROR), reason=f"native library unbuildable: {_BUILD_ERROR}"
)


def _records(n=20, seed=1):
    # Variable lengths to exercise the max-length buffer path.
    return [bytes([(seed * 31 + i + j) % 256 for j in range(5 + 13 * i)])
            for i in range(n)]


def test_crc32c_known_vector():
    # RFC 3720 check value for "123456789".
    assert crc32c(b"123456789") == 0xE3069283
    assert crc32c(b"") == 0


def test_crc_native_matches_python():
    from pddl_tpu.data.tfrecord import native_crc32c, native_masked_crc32c

    for data in (b"", b"a", b"123456789", bytes(range(256)) * 7):
        assert native_crc32c(data) == crc32c(data)
        assert native_masked_crc32c(data) == masked_crc32c(data)


def test_python_roundtrip(tmp_path):
    path = str(tmp_path / "a.tfrecord")
    recs = _records()
    assert write_tfrecord(path, recs) == len(recs)
    assert list(read_tfrecord(path)) == recs


def test_native_reader_sequential(tmp_path):
    path = str(tmp_path / "a.tfrecord")
    recs = _records()
    write_tfrecord(path, recs)
    reader = TFRecordReader([path])
    assert reader.num_records == len(recs)
    assert list(reader) == recs
    # Re-iterable: second epoch identical without shuffle.
    assert list(reader) == recs
    reader.close()


def test_tf_interop_both_directions(tmp_path):
    tf = pytest.importorskip("tensorflow")
    recs = _records()

    ours = str(tmp_path / "ours.tfrecord")
    write_tfrecord(ours, recs)
    via_tf = [t.numpy() for t in tf.data.TFRecordDataset(ours)]
    assert via_tf == recs

    theirs = str(tmp_path / "tf.tfrecord")
    with tf.io.TFRecordWriter(theirs) as w:
        for r in recs:
            w.write(r)
    assert list(TFRecordReader([theirs])) == recs


def test_sharding_partitions_global_sequence(tmp_path):
    paths = []
    recs = _records(n=30)
    for fi in range(3):
        p = str(tmp_path / f"s{fi}.tfrecord")
        write_tfrecord(p, recs[fi * 10:(fi + 1) * 10])
        paths.append(p)

    shards = [list(TFRecordReader(paths, shard_index=i, shard_count=4))
              for i in range(4)]
    # Every record exactly once across shards; each shard takes every 4th.
    assert sorted(b for s in shards for b in s) == sorted(recs)
    assert shards[0] == recs[0::4]
    assert shards[3] == recs[3::4]
    r = TFRecordReader(paths, shard_index=1, shard_count=4)
    assert r.total_records == 30 and r.num_records == len(shards[1])


def test_shuffle_deterministic_and_reshuffled(tmp_path):
    path = str(tmp_path / "a.tfrecord")
    recs = _records(n=64)
    write_tfrecord(path, recs)

    r1 = TFRecordReader([path], shuffle=True, seed=7)
    r2 = TFRecordReader([path], shuffle=True, seed=7)
    e1, e2 = list(r1), list(r2)
    assert e1 == e2  # same seed, same epoch -> same order
    assert sorted(e1) == sorted(recs)
    assert e1 != recs  # actually shuffled (64! leaves ~0 chance)
    assert list(r1) != e1  # epoch 2 reshuffles...
    assert list(TFRecordReader([path], shuffle=True, seed=8)) != e1


def test_zero_length_records_roundtrip(tmp_path):
    # Empty payloads are legal TFRecord framing and must not be mistaken
    # for the end-of-epoch sentinel.
    path = str(tmp_path / "a.tfrecord")
    recs = [b"", b"x", b"", b"yz"]
    write_tfrecord(path, recs)
    assert list(read_tfrecord(path)) == recs
    reader = TFRecordReader([path])
    assert list(reader) == recs
    assert list(reader) == recs  # second epoch too
    reader.close()


def test_corrupt_payload_detected(tmp_path):
    path = str(tmp_path / "a.tfrecord")
    recs = _records(n=4)
    write_tfrecord(path, recs)
    with open(path, "r+b") as f:
        f.seek(12 + 2)  # inside record 0's payload
        b = f.read(1)
        f.seek(12 + 2)
        f.write(bytes([b[0] ^ 0xFF]))

    with pytest.raises(IOError):
        list(read_tfrecord(path))
    with pytest.raises(IOError):
        list(TFRecordReader([path]))
    # verify=False skips payload CRCs: the flipped byte flows through.
    got = list(TFRecordReader([path], verify=False))
    assert len(got) == 4 and got[1:] == recs[1:] and got[0] != recs[0]


def test_corrupt_length_rejected_at_open(tmp_path):
    path = str(tmp_path / "a.tfrecord")
    write_tfrecord(path, _records(n=2))
    with open(path, "r+b") as f:
        f.seek(0)
        f.write(struct.pack("<Q", 1 << 40))  # garbage length, bad CRC

    with pytest.raises(FileNotFoundError):
        TFRecordReader([path])
    with pytest.raises(IOError):
        list(read_tfrecord(path))


def test_pack_imagenet_tfrecords_to_native_loader(tmp_path):
    tf = pytest.importorskip("tensorflow")
    import numpy as np

    from pddl_tpu.data.native_loader import NativeLoader
    from pddl_tpu.data.pack import pack_imagenet_tfrecords

    rng = np.random.default_rng(0)
    n, size = 12, 16
    images = rng.integers(0, 255, (n, size, size, 3), np.uint8)
    paths = []
    for fi in range(2):
        p = str(tmp_path / f"train-{fi}.tfrecord")
        with tf.io.TFRecordWriter(p) as w:
            for i in range(fi * 6, fi * 6 + 6):
                ex = tf.train.Example(features=tf.train.Features(feature={
                    # PNG (lossless) so content checks are exact; the
                    # converter's decode_image handles JPEG identically.
                    "image/encoded": tf.train.Feature(bytes_list=tf.train.BytesList(
                        value=[tf.io.encode_png(images[i]).numpy()])),
                    "image/class/label": tf.train.Feature(int64_list=tf.train.Int64List(
                        value=[i + 1])),
                }))
                w.write(ex.SerializeToString())
        paths.append(p)

    out = str(tmp_path / "train.pdl1")
    wrote = pack_imagenet_tfrecords(paths, out, image_size=size,
                                    label_offset=-1)
    assert wrote == n

    loader = NativeLoader([out], batch_size=4, shuffle=False,
                          drop_remainder=False)
    batches = list(loader)
    got_labels = sorted(int(l) for b in batches for l in b["label"])
    assert got_labels == list(range(n))
    assert batches[0]["image"].shape == (4, size, size, 3)
    first_label = int(batches[0]["label"][0])
    np.testing.assert_array_equal(batches[0]["image"][0],
                                  images[first_label])
    loader.close()

    # Sharded packing partitions the global record sequence.
    s0 = str(tmp_path / "s0.pdl1")
    s1 = str(tmp_path / "s1.pdl1")
    n0 = pack_imagenet_tfrecords(paths, s0, image_size=size,
                                 shard_index=0, shard_count=2)
    n1 = pack_imagenet_tfrecords(paths, s1, image_size=size,
                                 shard_index=1, shard_count=2)
    assert n0 + n1 == n


def test_open_tfrecords_fallback(tmp_path):
    path = str(tmp_path / "a.tfrecord")
    recs = _records(n=6)
    write_tfrecord(path, recs)
    assert list(open_tfrecords([path])) == recs
    py = open_tfrecords([path], native=False)
    assert list(py) == recs
    # Fallback mirrors the native reader surface.
    assert len(py) == py.num_records == py.total_records == 6
    py.close()
    with pytest.raises(RuntimeError):
        open_tfrecords([path], native=False, shuffle=True)
