"""Crash-resilient training (`pddl_tpu/train/faults.py`, the Trainer's
guarded device-call boundary, verified step-granular checkpointing, and
exact resume), CPU.

The contracts under test — the training mirror of
`tests/test_serve_faults.py`:

- **Chaos matrix** (3 seeds x {transient storm, kill-at-step,
  corrupt-latest-checkpoint}, ``@pytest.mark.chaos``): every run
  terminates, resumes (in-process or via restart), and its final
  parameters are BIT-IDENTICAL to the uninterrupted run, with zero
  recompiles across every recovery transition.
- **Retry**: a transient burst within the budget recovers in place —
  no restore, same params, events traced at exact (step, site)
  coordinates.
- **Restore+replay**: a burst past the budget (or any OOM) restores
  the last VERIFIED checkpoint in-process and replays forward from the
  batch replay buffer — CheckFreq-style recovery, bit-exact.
- **Verified checkpoints**: saves embed per-leaf checksums + loader
  position; a corrupted latest save is detected (checksum or parse)
  and restore falls back to the previous verified step.
- **Exact restart**: a KILLed run restarted with ``fit(resume=...)``
  continues MID-epoch from the saved loader position and ends
  bit-exact with the clean run.
- **Worker loss**: shared-dir heartbeats detect a silent worker,
  propagate a coordinated-restart marker, and stop survivors at a
  batch boundary (the cross-process leg rides
  ``tests/test_multiprocess.py``).
- **Exposition**: training fault/recovery counters render through the
  same Prometheus path serving uses, drift-guarded both directions.
"""

import json
import os

import jax
import numpy as np
import pytest

from pddl_tpu.ckpt.checkpoint import (
    CheckpointCorruptError,
    CheckpointEveryN,
    Checkpointer,
)
from pddl_tpu.data.synthetic import SyntheticImageClassification
from pddl_tpu.models.resnet import tiny_resnet
from pddl_tpu.obs import RequestTracer, parse_prometheus_text, train_exposition
from pddl_tpu.parallel.single import SingleDeviceStrategy
from pddl_tpu.train.faults import (
    FaultKind,
    FaultSpec,
    KillPoint,
    TrainFaultPlan,
    TrainStateLost,
)
from pddl_tpu.train.loop import Trainer

EPOCHS, SPE = 2, 5  # 10 optimizer steps end to end


def _dataset():
    return SyntheticImageClassification(batch_size=8, image_size=16,
                                        num_classes=8, seed=3)


def _trainer(**kw):
    kw.setdefault("retry_sleep", lambda s: None)  # tests never wall-wait
    return Trainer(tiny_resnet(num_classes=8), optimizer="adam",
                   learning_rate=1e-2, strategy=SingleDeviceStrategy(),
                   seed=0, **kw)


def _params(tr):
    return [np.asarray(x)
            for x in jax.tree.leaves(jax.device_get(tr.state.params))]


def _assert_bit_exact(a, b):
    assert len(a) == len(b)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


@pytest.fixture(scope="module")
def clean_params():
    tr = _trainer()
    tr.fit(_dataset(), epochs=EPOCHS, steps_per_epoch=SPE, verbose=0)
    return _params(tr)


def _ckpt_cb(directory, every=2):
    return CheckpointEveryN(str(directory), every_n_steps=every,
                            async_save=False)


def _corrupt_newest_step(directory):
    """Flip bytes in every data file of the newest finalized step —
    whether that breaks structural parsing or 'only' the bytes, restore
    must detect it (parse failure or checksum mismatch) and fall back."""
    steps = [int(n) for n in os.listdir(directory) if n.isdigit()]
    newest = os.path.join(str(directory), str(max(steps)), "state")
    flipped = 0
    for root, _, files in os.walk(newest):
        for name in files:
            path = os.path.join(root, name)
            size = os.path.getsize(path)
            if size < 32:
                continue
            with open(path, "r+b") as f:
                f.seek(size // 2)
                b = f.read(1)
                f.seek(size // 2)
                f.write(bytes([b[0] ^ 0xFF]))
            flipped += 1
    assert flipped, f"nothing corruptible under {newest}"
    return max(steps)


# ------------------------------------------------------------ chaos matrix
_PROFILES = ("transient_storm", "kill_at_step", "corrupt_latest")


@pytest.mark.chaos
@pytest.mark.parametrize("seed", [0, 1, 2])
@pytest.mark.parametrize("profile", _PROFILES)
def test_chaos_matrix(tmp_path, clean_params, pin_zero_recompiles, seed,
                      profile):
    """Seeded chaos over the training loop: every scenario terminates,
    resumes (in-process restore+replay or kill+restart), matches the
    clean run BIT-EXACTLY, and compiles nothing new across recovery."""
    ckdir = str(tmp_path / "ck")
    if profile == "transient_storm":
        # Random transients, some bursts long enough to exhaust the
        # retry budget and force restore+replay (count > max_retries
        # scheduled on top of the rate draws so every seed exercises
        # BOTH paths).
        plan = TrainFaultPlan(
            seed=seed, transient_rate=0.25, max_random_injections=6,
            scheduled=[FaultSpec(4 + seed, "train_step",
                                 FaultKind.TRANSIENT, count=10)])
        tr = _trainer(fault_plan=plan)
        tr.fit(_dataset(), epochs=EPOCHS, steps_per_epoch=SPE, verbose=0,
               callbacks=[_ckpt_cb(ckdir)])
        pin_zero_recompiles(tr)
        assert plan.total_injected > 0
        assert tr.fault_stats["recoveries"] >= 1
        final = tr
    elif profile == "kill_at_step":
        # Adversarial coordinate: mid-epoch, off the checkpoint cadence.
        kill_at = 5 + seed
        plan = TrainFaultPlan(
            seed=seed,
            scheduled=[FaultSpec(kill_at, "train_step", FaultKind.KILL)])
        tr = _trainer(fault_plan=plan)
        with pytest.raises(KillPoint):
            tr.fit(_dataset(), epochs=EPOCHS, steps_per_epoch=SPE,
                   verbose=0, callbacks=[_ckpt_cb(ckdir)])
        assert int(jax.device_get(tr.state.step)) == kill_at
        # Restart: a FRESH process's trainer resumes mid-epoch.
        final = _trainer()
        final.fit(_dataset(), epochs=EPOCHS, steps_per_epoch=SPE,
                  verbose=0, resume=ckdir, callbacks=[_ckpt_cb(ckdir)])
        pin_zero_recompiles(final)
    else:  # corrupt_latest
        tr = _trainer()
        tr.fit(_dataset(), epochs=1, steps_per_epoch=SPE, verbose=0,
               callbacks=[_ckpt_cb(ckdir)])
        corrupted = _corrupt_newest_step(ckdir)
        final = _trainer()
        final.fit(_dataset(), epochs=EPOCHS, steps_per_epoch=SPE,
                  verbose=0, resume=ckdir, callbacks=[_ckpt_cb(ckdir)])
        pin_zero_recompiles(final)
        # The corrupted save was skipped: the resumed run restored an
        # EARLIER step and recomputed forward.
        assert corrupted > 0
    assert int(jax.device_get(final.state.step)) == EPOCHS * SPE
    _assert_bit_exact(_params(final), clean_params)


# ------------------------------------------------------- targeted legs
def test_transient_within_budget_retries_in_place(clean_params):
    plan = TrainFaultPlan(scheduled=[
        FaultSpec(3, "train_step", FaultKind.TRANSIENT, count=2)])
    tracer = RequestTracer()
    tr = _trainer(fault_plan=plan, tracer=tracer)
    tr.fit(_dataset(), epochs=EPOCHS, steps_per_epoch=SPE, verbose=0)
    _assert_bit_exact(_params(tr), clean_params)
    assert tr.fault_stats["retries"] == 2
    assert tr.fault_stats["recoveries"] == 0
    # Injections and retries surface in the trace at the EXACT
    # (step, site) coordinates the plan fired at.
    inj = tracer.events_named("fault_injected")
    assert [(e["step"], e["site"]) for e in inj] == [(3, "train_step")] * 2
    ret = tracer.events_named("retry")
    assert [(e["step"], e["site"], e["attempt"]) for e in ret] == \
        [(3, "train_step", 1), (3, "train_step", 2)]


def test_retries_exhausted_restores_and_replays(tmp_path, clean_params,
                                                pin_zero_recompiles):
    plan = TrainFaultPlan(scheduled=[
        FaultSpec(7, "train_step", FaultKind.TRANSIENT, count=4)])
    tracer = RequestTracer()
    tr = _trainer(fault_plan=plan, tracer=tracer)
    tr.fit(_dataset(), epochs=EPOCHS, steps_per_epoch=SPE, verbose=0,
           callbacks=[_ckpt_cb(tmp_path / "ck")])
    pin_zero_recompiles(tr)
    _assert_bit_exact(_params(tr), clean_params)
    assert tr.fault_stats["recoveries"] == 1
    # Saved at step 6 (every 2), failed at 7: exactly one replayed step.
    assert tr.fault_stats["replayed_steps"] == 1
    restore, = tracer.events_named("restore")
    assert (restore["step"], restore["restored_step"]) == (7, 6)
    recovery, = tracer.events_named("recovery")
    assert recovery["replayed"] == 1


def test_oom_escalates_straight_to_restore(tmp_path, clean_params):
    plan = TrainFaultPlan(scheduled=[
        FaultSpec(5, "train_step", FaultKind.OOM)])
    tr = _trainer(fault_plan=plan)
    tr.fit(_dataset(), epochs=EPOCHS, steps_per_epoch=SPE, verbose=0,
           callbacks=[_ckpt_cb(tmp_path / "ck")])
    _assert_bit_exact(_params(tr), clean_params)
    # No blind retry of a failed allocation: straight to restore.
    assert tr.fault_stats["retries"] == 0
    assert tr.fault_stats["recoveries"] == 1


def test_exhausted_retries_without_recovery_source_raise():
    plan = TrainFaultPlan(scheduled=[
        FaultSpec(2, "train_step", FaultKind.TRANSIENT, count=10)])
    tr = _trainer(fault_plan=plan)
    with pytest.raises(TrainStateLost):
        tr.fit(_dataset(), epochs=1, steps_per_epoch=SPE, verbose=0)


def test_latency_fault_delays_but_completes(clean_params):
    slept = []
    plan = TrainFaultPlan(scheduled=[
        FaultSpec(1, "train_step", FaultKind.LATENCY),
        FaultSpec(6, "train_step", FaultKind.LATENCY)],
        latency_s=0.001, sleep_fn=slept.append)
    tr = _trainer(fault_plan=plan)
    tr.fit(_dataset(), epochs=EPOCHS, steps_per_epoch=SPE, verbose=0)
    _assert_bit_exact(_params(tr), clean_params)
    assert slept == [0.001, 0.001]
    assert tr.fault_stats["retries"] == 0


def test_eval_transient_retries_in_place_and_exhaustion_raises():
    # Within budget: evaluate() succeeds through retries.
    plan = TrainFaultPlan(scheduled=[
        FaultSpec(SPE, "eval_step", FaultKind.TRANSIENT, count=2)])
    tr = _trainer(fault_plan=plan)
    tr.fit(_dataset(), epochs=1, steps_per_epoch=SPE, verbose=0)
    logs = tr.evaluate(_dataset(), steps=2)
    assert np.isfinite(logs["loss"])
    assert tr.fault_stats["retries"] == 2
    # Past budget: eval mutates nothing — the device error surfaces
    # as itself (no bogus restore of untouched state).
    plan2 = TrainFaultPlan(scheduled=[
        FaultSpec(SPE, "eval_step", FaultKind.TRANSIENT, count=10)])
    tr2 = _trainer(fault_plan=plan2)
    tr2.fit(_dataset(), epochs=1, steps_per_epoch=SPE, verbose=0)
    from pddl_tpu.train.faults import InjectedTransientError

    with pytest.raises(InjectedTransientError):
        tr2.evaluate(_dataset(), steps=2)


# ------------------------------------------------- exact resume details
def test_kill_and_restart_resume_is_mid_epoch_and_bit_exact(
        tmp_path, clean_params, pin_zero_recompiles):
    """The acceptance pin, spelled out: kill at an adversarial step
    (mid-epoch, off the save cadence), restart from the step-granular
    checkpoint including loader state, end bit-exact — and the resumed
    run's history shows it re-entered the INTERRUPTED epoch, not the
    next one."""
    ckdir = str(tmp_path / "ck")
    plan = TrainFaultPlan(scheduled=[
        FaultSpec(7, "train_step", FaultKind.KILL)])
    tr = _trainer(fault_plan=plan)
    with pytest.raises(KillPoint):
        tr.fit(_dataset(), epochs=EPOCHS, steps_per_epoch=SPE, verbose=0,
               callbacks=[_ckpt_cb(ckdir)])

    # The newest save carries step-granular loader metadata.
    ck = Checkpointer(ckdir, read_only=True)
    try:
        meta = ck.metadata()
        assert meta["loader"] == {"epoch": 1, "step_in_epoch": 1,
                                  "batches_consumed": 6}
        assert meta["checksums"]  # verified save
    finally:
        ck.close()

    tr2 = _trainer()
    hist = tr2.fit(_dataset(), epochs=EPOCHS, steps_per_epoch=SPE,
                   verbose=0, resume=ckdir)
    pin_zero_recompiles(tr2)
    # Only the interrupted epoch (index 1) completes after resume.
    assert hist.epoch == [1]
    assert int(jax.device_get(tr2.state.step)) == EPOCHS * SPE
    _assert_bit_exact(_params(tr2), clean_params)


def test_resume_empty_directory_starts_fresh(tmp_path, clean_params):
    """The same command line serves first launch and restart: an empty
    checkpoint directory is a fresh run, not an error."""
    tr = _trainer()
    tr.fit(_dataset(), epochs=EPOCHS, steps_per_epoch=SPE, verbose=0,
           resume=str(tmp_path / "never_written"))
    _assert_bit_exact(_params(tr), clean_params)


def test_resume_without_steps_per_epoch_skips_within_epoch(tmp_path):
    """Finite re-iterable data (no steps_per_epoch): the resumed epoch
    skips exactly the batches it already consumed."""
    class Finite:
        def __init__(self, n=SPE):
            self.n = n
            self.ds = _dataset()

        def __iter__(self):
            return (self.ds.batch(i) for i in range(self.n))

    clean = _trainer()
    clean.fit(Finite(), epochs=2, verbose=0)

    ckdir = str(tmp_path / "ck")
    plan = TrainFaultPlan(scheduled=[
        FaultSpec(7, "train_step", FaultKind.KILL)])
    tr = _trainer(fault_plan=plan)
    with pytest.raises(KillPoint):
        tr.fit(Finite(), epochs=2, verbose=0, callbacks=[_ckpt_cb(ckdir)])
    tr2 = _trainer()
    tr2.fit(Finite(), epochs=2, verbose=0, resume=ckdir)
    _assert_bit_exact(_params(tr2), _params(clean))


def test_resume_skip_reiterates_finite_stream_with_steps_per_epoch(
        tmp_path):
    """steps_per_epoch over a FINITE re-iterable wraps around
    (_repeating); the resume skip must follow the same wrap-around when
    the consumed count exceeds one pass — not die at StopIteration."""
    class Finite:
        def __init__(self, n=6):
            self.n = n
            self.ds = _dataset()

        def __iter__(self):
            return (self.ds.batch(i) for i in range(self.n))

    clean = _trainer()
    clean.fit(Finite(), epochs=2, steps_per_epoch=5, verbose=0)

    ckdir = str(tmp_path / "ck")
    plan = TrainFaultPlan(scheduled=[
        FaultSpec(8, "train_step", FaultKind.KILL)])  # 8 consumed > 6/pass
    tr = _trainer(fault_plan=plan)
    with pytest.raises(KillPoint):
        tr.fit(Finite(), epochs=2, steps_per_epoch=5, verbose=0,
               callbacks=[_ckpt_cb(ckdir)])
    tr2 = _trainer()
    tr2.fit(Finite(), epochs=2, steps_per_epoch=5, verbose=0, resume=ckdir)
    _assert_bit_exact(_params(tr2), _params(clean))


def test_preemption_delegates_grace_save_to_checkpoint_every_n(tmp_path):
    """One writing manager per directory: PreemptionCheckpoint with a
    delegate saves through CheckpointEveryN — idempotent when the
    signal lands exactly on a save-cadence batch."""
    import os as _os
    import signal as _signal

    from pddl_tpu.utils.preemption import PreemptionCheckpoint

    class Sig:
        def set_trainer(self, t):
            self.trainer = t

        def on_train_begin(self, state):
            return None

        def on_train_end(self, state, logs):
            return None

        def on_epoch_begin(self, epoch, state):
            return None

        def on_epoch_end(self, epoch, state, logs):
            return None

        def on_train_batch_end(self, step, state, logs):
            if step == 3:  # lands ON the every-2 cadence (step 4 saved)
                _os.kill(_os.getpid(), _signal.SIGTERM)
            return None

    ckdir = str(tmp_path / "ck")
    cen = _ckpt_cb(ckdir, every=2)
    tr = _trainer()
    tr.fit(_dataset(), epochs=EPOCHS, steps_per_epoch=SPE, verbose=0,
           callbacks=[Sig(), cen, PreemptionCheckpoint(delegate=cen)])
    assert int(jax.device_get(tr.state.step)) == 4
    ck = Checkpointer(ckdir, read_only=True)
    try:
        # The cadence saved step 4; the grace save was the idempotent
        # no-op, not a second-manager collision.
        assert ck.latest_step() == 4
        assert ck.metadata(4)["loader"]["step_in_epoch"] == 4
    finally:
        ck.close()
    with pytest.raises(ValueError, match="exactly one"):
        PreemptionCheckpoint(ckdir, delegate=cen)


def test_with_offset_repositions_synthetic_streams():
    ds = _dataset()
    shifted = ds.with_offset(3)
    np.testing.assert_array_equal(shifted.batch(0)["image"],
                                  ds.batch(3)["image"])
    from pddl_tpu.data.synthetic import SyntheticLanguageModeling

    lm = SyntheticLanguageModeling(batch_size=4, seq_len=8, seed=1)
    np.testing.assert_array_equal(lm.with_offset(2).batch(1)["tokens"],
                                  lm.batch(3)["tokens"])


# --------------------------------------------- checkpoint verification
def test_tampered_checksum_metadata_detected(tmp_path):
    """A checksum mismatch (not just a torn file) is detected: restore
    with an explicit step raises; restore without one falls back to the
    previous verified save."""
    ckdir = str(tmp_path / "ck")
    tr = _trainer()
    tr.fit(_dataset(), epochs=1, steps_per_epoch=SPE, verbose=0,
           callbacks=[_ckpt_cb(ckdir)])
    ck = Checkpointer(ckdir, async_save=False)
    try:
        newest = ck.latest_step()
        meta_path = None
        for root, _, files in os.walk(os.path.join(ckdir, str(newest))):
            for name in files:
                if name.endswith(".json") or "metadata" in name:
                    p = os.path.join(root, name)
                    try:
                        doc = json.load(open(p))
                    except Exception:  # noqa: BLE001
                        continue
                    if isinstance(doc, dict) and "checksums" in doc:
                        meta_path = p
                        first = next(iter(doc["checksums"]))
                        doc["checksums"][first] = "deadbeef"
                        json.dump(doc, open(p, "w"))
        assert meta_path, "no checksum metadata found on disk"
        with pytest.raises(CheckpointCorruptError):
            ck.restore(tr.state, step=newest)
        restored = ck.restore(tr.state)  # falls back
        assert int(jax.device_get(restored.step)) < newest
    finally:
        ck.close()


def test_torn_latest_save_falls_back(tmp_path):
    """A torn save (files missing — crash mid-write after finalize
    bookkeeping) restores the previous step instead of raising."""
    ckdir = str(tmp_path / "ck")
    tr = _trainer()
    tr.fit(_dataset(), epochs=1, steps_per_epoch=SPE, verbose=0,
           callbacks=[_ckpt_cb(ckdir)])
    ck = Checkpointer(ckdir, async_save=False)
    try:
        newest = ck.latest_step()
        state_dir = os.path.join(ckdir, str(newest), "state")
        for root, _, files in os.walk(state_dir):
            for name in files:
                os.remove(os.path.join(root, name))
        restored = ck.restore(tr.state)
        assert int(jax.device_get(restored.step)) < newest
    finally:
        ck.close()


def test_checkpoint_every_n_writes_verified_saves(tmp_path):
    ckdir = str(tmp_path / "ck")
    cb = _ckpt_cb(ckdir, every=2)
    tr = _trainer()
    tr.fit(_dataset(), epochs=1, steps_per_epoch=SPE, verbose=0,
           callbacks=[cb])
    assert cb.saves == 2  # steps 2 and 4
    ck = Checkpointer(ckdir, read_only=True)
    try:
        assert ck.all_steps() == [2, 4]
        meta = ck.metadata(4)
        assert meta["loader"] == {"epoch": 0, "step_in_epoch": 4,
                                  "batches_consumed": 4}
        restored = ck.restore(tr.state, step=4)  # verifies checksums
        assert ck.verify(restored, 4)
    finally:
        ck.close()
    assert tr.fault_stats["checkpoints_saved"] == 2


def test_checkpoint_every_n_rejects_unsafe_retention(tmp_path):
    with pytest.raises(ValueError, match="max_to_keep"):
        CheckpointEveryN(str(tmp_path), max_to_keep=1)
    with pytest.raises(ValueError, match="every_n_steps"):
        CheckpointEveryN(str(tmp_path), every_n_steps=0)


# ------------------------------------------------------- worker loss
def test_heartbeat_monitor_detects_silent_worker(tmp_path):
    from pddl_tpu.parallel.multiworker import HeartbeatMonitor, WorkerLost

    now = [1000.0]
    clock = lambda: now[0]  # noqa: E731
    a = HeartbeatMonitor(str(tmp_path), process_id=0, num_processes=2,
                         timeout_s=5.0, clock=clock)
    b = HeartbeatMonitor(str(tmp_path), process_id=1, num_processes=2,
                         timeout_s=5.0, clock=clock)
    a.start()
    b.start()
    a.check()  # both fresh
    now[0] += 4.0
    b.beat()
    a.beat()
    a.check()  # b beat recently
    now[0] += 6.0
    a.beat()   # a alive, b silent for 6s > 5s
    with pytest.raises(WorkerLost) as e:
        a.check()
    assert e.value.lost == [1]
    # b's view symmetrically blames a... after a's last beat goes stale.
    now[0] += 6.0
    b.beat()
    assert b.failed() == [0]


def test_heartbeat_restart_marker_roundtrip(tmp_path):
    from pddl_tpu.parallel.multiworker import HeartbeatMonitor

    a = HeartbeatMonitor(str(tmp_path), process_id=0, num_processes=2,
                         timeout_s=5.0)
    b = HeartbeatMonitor(str(tmp_path), process_id=1, num_processes=2,
                         timeout_s=5.0)
    assert not b.restart_requested()
    a.request_restart("drill")
    assert b.restart_requested()
    b.clear_restart()
    assert not a.restart_requested()


def test_heartbeat_callback_stops_training_and_reports(tmp_path):
    """A phantom worker that never beats: the callback detects it at a
    batch boundary, requests the coordinated restart, stops training
    cleanly (checkpoint callbacks still flush), and re-raises at train
    end so the supervisor sees the failure."""
    from pddl_tpu.parallel.multiworker import (
        HeartbeatCallback,
        HeartbeatMonitor,
        WorkerLost,
    )

    now = [0.0]
    mon = HeartbeatMonitor(str(tmp_path / "hb"), process_id=0,
                           num_processes=2, timeout_s=0.5,
                           clock=lambda: now[0])
    cb = HeartbeatCallback(mon, check_every_steps=2)

    class Tick:
        def set_trainer(self, t):
            self.trainer = t

        def on_train_begin(self, state):
            return None

        def on_train_end(self, state, logs):
            return None

        def on_epoch_begin(self, epoch, state):
            return None

        def on_epoch_end(self, epoch, state, logs):
            return None

        def on_train_batch_end(self, step, state, logs):
            now[0] += 0.3  # 2 steps outrun the 0.5s timeout
            return None

    tr = _trainer()
    with pytest.raises(WorkerLost):
        tr.fit(_dataset(), epochs=1, steps_per_epoch=SPE, verbose=0,
               callbacks=[Tick(), cb])
    assert mon.restart_requested()
    assert int(jax.device_get(tr.state.step)) < SPE  # stopped early

    # An OBSERVER (another worker's callback) sees a marker dropped
    # MID-training and stops WITHOUT raising — only the detector
    # reports. (A marker left over from a previous incarnation is
    # cleared at train begin instead: relaunches must start clean.)
    mon2 = HeartbeatMonitor(str(tmp_path / "hb"), process_id=1,
                            num_processes=2, timeout_s=1e9)
    # Marker polling rides the check cadence (shared-FS metadata cost);
    # check every batch here so the observer reacts at the next boundary.
    cb2 = HeartbeatCallback(mon2, check_every_steps=1)

    class DropMarker:
        def set_trainer(self, t):
            self.trainer = t

        def on_train_begin(self, state):
            return None

        def on_train_end(self, state, logs):
            return None

        def on_epoch_begin(self, epoch, state):
            return None

        def on_epoch_end(self, epoch, state, logs):
            return None

        def on_train_batch_end(self, step, state, logs):
            if step == 1:  # another worker requests a restart
                mon.request_restart("peer detection")
            return None

    tr2 = _trainer()
    tr2.fit(_dataset(), epochs=1, steps_per_epoch=SPE, verbose=0,
            callbacks=[DropMarker(), cb2])
    assert cb2.lost is None  # observer, not detector
    assert int(jax.device_get(tr2.state.step)) == 2  # stopped at marker


# ------------------------------------------------------- exposition
def test_train_exposition_renders_every_snapshot_key(tmp_path):
    """Drift guard, both directions: every fault_snapshot key lands in
    the exposition (flat or labeled), and the strict parser round-trips
    the text — training rides the SAME export path as serving."""
    plan = TrainFaultPlan(scheduled=[
        FaultSpec(3, "train_step", FaultKind.TRANSIENT, count=4)])
    tr = _trainer(fault_plan=plan)
    tr.fit(_dataset(), epochs=1, steps_per_epoch=SPE, verbose=0,
           callbacks=[_ckpt_cb(tmp_path / "ck")])
    snap = tr.fault_snapshot()
    assert snap["retries"] == 3
    assert snap["recoveries"] == 1
    assert snap["faults_injected"]["transient"] == 4
    assert snap["compile_counts"] == {"train_step": 1}

    text = train_exposition(tr)
    samples, types = parse_prometheus_text(text)
    names = {n for n, _ in samples}
    for key in snap:
        assert any(f"pddl_train_{key}" in n for n in names), \
            f"snapshot key {key!r} missing from exposition"
    assert types["pddl_train_retries_total"] == "counter"
    assert samples[("pddl_train_retries_total", ())] == 3.0
    assert samples[("pddl_train_compile_counts",
                    (("key", "train_step"),))] == 1.0


def test_train_fault_plan_validates_sites():
    with pytest.raises(ValueError, match="unknown scheduled site"):
        TrainFaultPlan(scheduled=[FaultSpec(0, "tick",
                                            FaultKind.TRANSIENT)])
    with pytest.raises(ValueError, match="unknown fault site"):
        TrainFaultPlan(sites=["prefill"])
    # The serving plan keeps its own vocabulary — shared machinery,
    # separate site namespaces.
    from pddl_tpu.serve.faults import FaultPlan

    assert "tick" in FaultPlan.SITES
    assert "train_step" in TrainFaultPlan.SITES