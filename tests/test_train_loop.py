"""End-to-end training on the fake 8-device mesh: the minimum slice of
SURVEY.md §7 build order step 1 (loss decreases, metrics flow, History)."""

import jax
import numpy as np
import pytest

from pddl_tpu.data.synthetic import SyntheticImageClassification
from pddl_tpu.models.resnet import tiny_resnet
from pddl_tpu.ops.augment import standard_augment
from pddl_tpu.parallel import MirroredStrategy, SingleDeviceStrategy
from pddl_tpu.train.loop import Trainer


def _dataset(batch=32, **kw):
    kw.setdefault("image_size", 32)
    kw.setdefault("num_classes", 10)
    kw.setdefault("signal_strength", 3.0)
    return SyntheticImageClassification(batch_size=batch, **kw)


def test_fit_loss_decreases_single_device():
    tr = Trainer(tiny_resnet(num_classes=10), learning_rate=1e-2,
                 strategy=SingleDeviceStrategy())
    h = tr.fit(_dataset(16), epochs=3, steps_per_epoch=6, verbose=0)
    losses = h.history["loss"]
    assert losses[-1] < losses[0] * 0.8
    assert h.history["accuracy"][-1] > h.history["accuracy"][0]


def test_fit_mirrored_8_devices():
    strat = MirroredStrategy()
    assert strat.num_replicas_in_sync == 8
    # global batch = 4 * 8, the reference's 32*n arithmetic
    # (imagenet-resnet50-mirror.py:54)
    global_batch = strat.scale_batch_size(4)
    assert global_batch == 32
    tr = Trainer(tiny_resnet(num_classes=10), learning_rate=1e-2, strategy=strat)
    h = tr.fit(_dataset(global_batch), epochs=2, steps_per_epoch=6, verbose=0)
    assert h.history["loss"][-1] < h.history["loss"][0]
    # params stay replicated; batch was sharded 8 ways
    leaf = jax.tree.leaves(tr.state.params)[0]
    assert leaf.sharding.is_fully_replicated


def test_validation_metrics_and_history():
    ds = _dataset(16)
    val = _dataset(16, index_offset=10_000)
    tr = Trainer(tiny_resnet(num_classes=10), learning_rate=1e-2,
                 strategy=SingleDeviceStrategy())
    h = tr.fit(ds, epochs=2, steps_per_epoch=4, validation_data=val,
               validation_steps=2, verbose=0)
    assert set(h.history) >= {"loss", "accuracy", "val_loss", "val_accuracy"}
    assert len(h.epoch) == 2


def test_mirrored_equals_single_device_math():
    """Same global batch, same seed => mirrored DP must match single-device
    numerics (the sync-SPMD guarantee NCCL gave the reference)."""
    ds = _dataset(16)
    t1 = Trainer(tiny_resnet(num_classes=10), learning_rate=1e-2,
                 strategy=SingleDeviceStrategy(), seed=7)
    t8 = Trainer(tiny_resnet(num_classes=10), learning_rate=1e-2,
                 strategy=MirroredStrategy(), seed=7)
    h1 = t1.fit(ds, epochs=1, steps_per_epoch=4, verbose=0)
    h8 = t8.fit(ds, epochs=1, steps_per_epoch=4, verbose=0)
    np.testing.assert_allclose(
        h1.history["loss"][0], h8.history["loss"][0], rtol=2e-4
    )
    p1 = jax.device_get(jax.tree.leaves(t1.state.params)[0])
    p8 = jax.device_get(jax.tree.leaves(t8.state.params)[0])
    np.testing.assert_allclose(p1, p8, rtol=5e-4, atol=5e-6)


def test_augmented_training_runs():
    tr = Trainer(
        tiny_resnet(num_classes=10), learning_rate=1e-2,
        strategy=MirroredStrategy(),
        augment=standard_augment(crop=28, flip=True, rescale_factor=None),
    )
    h = tr.fit(_dataset(32), epochs=1, steps_per_epoch=3, verbose=0)
    assert np.isfinite(h.history["loss"][0])


def test_predict_shape():
    tr = Trainer(tiny_resnet(num_classes=10), strategy=SingleDeviceStrategy())
    tr.fit(_dataset(16), epochs=1, steps_per_epoch=2, verbose=0)
    out = tr.predict(np.zeros((8, 32, 32, 3), np.float32))
    assert out.shape == (8, 10)


def test_evaluate_before_fit_raises():
    tr = Trainer(tiny_resnet(num_classes=10), strategy=SingleDeviceStrategy())
    with pytest.raises(RuntimeError):
        tr.evaluate(_dataset(16), steps=1)


def test_restore_best_weights_survives_donation():
    """EarlyStopping must deep-copy its snapshot: live param buffers are
    donated by the next jitted step (regression test)."""
    from pddl_tpu.train.callbacks import EarlyStopping

    noise = SyntheticImageClassification(
        batch_size=16, image_size=32, num_classes=10, signal_strength=0.0
    )
    tr = Trainer(tiny_resnet(num_classes=10), learning_rate=1e-2,
                 strategy=SingleDeviceStrategy())
    cb = EarlyStopping(monitor="val_loss", patience=1, min_delta=10.0,
                       restore_best_weights=True)
    tr.fit(noise, epochs=10, steps_per_epoch=1, validation_data=noise,
           validation_steps=1, callbacks=[cb], verbose=0)
    # restored params must be alive and usable
    out = tr.predict(np.zeros((2, 32, 32, 3), np.float32))
    assert np.all(np.isfinite(out))


def test_generator_dataset_trains_on_all_batches():
    """The batch consumed by lazy init must still be trained on; a 3-batch
    generator with steps_per_epoch=None must yield 3 steps (regression)."""
    ds = _dataset(16)
    seen = []

    def gen():
        for i in range(3):
            b = ds.batch(i)
            seen.append(i)
            yield b

    tr = Trainer(tiny_resnet(num_classes=10), learning_rate=1e-2,
                 strategy=SingleDeviceStrategy())
    tr.fit(gen(), epochs=1, verbose=0)
    assert seen == [0, 1, 2]
    assert int(jax.device_get(tr.state.step)) == 3


def test_one_shot_iterator_multi_epoch_raises():
    ds = _dataset(16)
    tr = Trainer(tiny_resnet(num_classes=10), strategy=SingleDeviceStrategy())
    with pytest.raises(ValueError, match="one-shot iterator"):
        tr.fit(iter([ds.batch(0), ds.batch(1)]), epochs=2, verbose=0)


def test_finite_reiterable_repeats_under_steps_per_epoch(caplog):
    """A finite re-iterable dataset + steps_per_epoch repeats implicitly:
    the reference's `.repeat()` + fixed-steps pattern
    (imagenet-resnet50-ps.py:118-119,143). 4 epochs x 3 steps = 12 steps
    must train through a 5-batch dataset (2.4 passes)."""
    ds = _dataset(16)
    passes = []

    class Finite:
        def __iter__(self):
            passes.append(len(passes))
            return iter([ds.batch(i) for i in range(5)])

    tr = Trainer(tiny_resnet(num_classes=10), learning_rate=1e-2,
                 strategy=SingleDeviceStrategy())
    h = tr.fit(Finite(), epochs=4, steps_per_epoch=3, verbose=0)
    assert int(jax.device_get(tr.state.step)) == 12
    assert len(h.epoch) == 4
    assert len(passes) >= 3  # the dataset really was re-iterated
    # The first re-pass announces itself ONCE with the observed pass size
    # (a mis-sized pipeline must not repeat silently).
    msgs = [r.getMessage() for r in caplog.records
            if "outlives the dataset" in r.getMessage()]
    assert len(msgs) == 1
    assert "5 batches/pass" in msgs[0]

    # A one-shot ITERATOR under steps_per_epoch still just ends: the epoch
    # that receives nothing raises rather than silently spinning.
    tr2 = Trainer(tiny_resnet(num_classes=10), learning_rate=1e-2,
                  strategy=SingleDeviceStrategy())
    with pytest.raises(ValueError, match="empty training dataset"):
        tr2.fit(iter([ds.batch(i) for i in range(4)]), epochs=3,
                steps_per_epoch=3, verbose=0)


def test_log_grad_norm_in_history():
    """log_grad_norm=True adds the global gradient L2 norm to the train
    logs (the observable the multichip equivalence gate compares)."""
    tr = Trainer(tiny_resnet(num_classes=10), learning_rate=1e-2,
                 strategy=SingleDeviceStrategy(), log_grad_norm=True)
    tr.fit(_dataset(16), epochs=2, steps_per_epoch=1, verbose=0)
    norms = tr.history.history["grad_norm"]
    assert len(norms) == 2
    assert all(np.isfinite(n) and n > 0 for n in norms)
    # Off by default: no spurious key in the logs.
    tr2 = Trainer(tiny_resnet(num_classes=10), learning_rate=1e-2,
                  strategy=SingleDeviceStrategy())
    tr2.fit(_dataset(16), epochs=1, steps_per_epoch=1, verbose=0)
    assert "grad_norm" not in tr2.history.history


def test_determinism_same_seed_bitwise():
    """Same seed -> bitwise-equal params after N steps (SURVEY.md §5 race
    detection: functional purity + fixed PRNG keys replace TSAN)."""
    def run():
        tr = Trainer(tiny_resnet(num_classes=10), learning_rate=1e-2,
                     strategy=MirroredStrategy(), seed=3)
        tr.fit(_dataset(32), epochs=1, steps_per_epoch=4, verbose=0)
        return jax.device_get(tr.state.params)

    a, b = run(), run()
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(x, y)


def test_eval_transform_applied_in_evaluate_and_predict():
    """Eval/predict must see the deterministic preprocessing counterpart of
    the train-time augmentation (Keras preprocessing layers run at inference
    too: Rescaling always, RandomCrop becomes a center crop)."""
    from pddl_tpu.ops.augment import standard_eval_transform

    ds = _dataset(16)
    tr = Trainer(
        tiny_resnet(num_classes=10), learning_rate=1e-2,
        strategy=SingleDeviceStrategy(),
        augment=standard_augment(crop=32, flip=True, rescale_factor=0.5),
        eval_transform=standard_eval_transform(crop=32, rescale_factor=0.5),
    )
    tr.fit(ds, epochs=1, steps_per_epoch=4, verbose=0)
    batch = ds.batch(0)
    # Rescaled inputs vs raw inputs must give different logits — proving the
    # transform runs in the eval path.
    logits_with = tr.predict(batch["image"])
    logs_with = tr.evaluate([batch])
    assert np.isfinite(logs_with["loss"])
    assert logits_with.shape == (16, 10)
    # Identity transform (raw 0..255-scale pixels) produces different logits.
    tr.eval_transform = None
    logits_raw = tr.predict(batch["image"])
    assert not np.allclose(logits_with, logits_raw)


def test_one_shot_validation_iterator_raises():
    ds = _dataset(16)
    tr = Trainer(tiny_resnet(num_classes=10), strategy=SingleDeviceStrategy())
    with pytest.raises(ValueError, match="one-shot iterator"):
        tr.fit(ds, epochs=2, steps_per_epoch=2, validation_data=iter(ds), verbose=0)
