"""Compiled LR schedules, parameter EMA, and TensorBoard logging.

All three are TPU-first upgrades over the reference's host-side control:
schedules run inside the jitted step (vs callbacks-only LR control,
``/root/reference/imagenet-resnet50.py:64``), EMA shadows update in the
same compiled update, and TensorBoard replaces the console-only
observability (``imagenet-resnet50.py:67``)."""

import glob
import os

import jax
import numpy as np
import pytest

from pddl_tpu.data.synthetic import SyntheticImageClassification
from pddl_tpu.models.resnet import ResNet
from pddl_tpu.train.loop import Trainer
from pddl_tpu.train.state import get_learning_rate, make_schedule


def _tiny_model(num_classes=8):
    return ResNet(stage_sizes=(1,), num_classes=num_classes,
                  width_multiplier=0.25, small_input_stem=True)


def _data(batch=16, classes=8, seed=0):
    return SyntheticImageClassification(
        batch_size=batch, image_size=16, num_classes=classes, seed=seed)


# --------------------------------------------------------------- schedules
def test_make_schedule_shapes():
    cos = make_schedule("cosine", 1.0, decay_steps=100, alpha=0.1)
    assert float(cos(0)) == pytest.approx(1.0)
    assert float(cos(100)) == pytest.approx(0.1)
    assert 0.1 < float(cos(50)) < 1.0

    warm = make_schedule("cosine", 1.0, decay_steps=100, warmup_steps=10)
    assert float(warm(0)) == pytest.approx(0.0)
    assert float(warm(10)) == pytest.approx(1.0)
    assert float(warm(100)) < 0.05

    exp = make_schedule("exponential", 1.0, decay_steps=10, decay_rate=0.5)
    assert float(exp(10)) == pytest.approx(0.5)

    lin = make_schedule("linear", 1.0, decay_steps=10, end_value=0.0)
    assert float(lin(5)) == pytest.approx(0.5)

    piece = make_schedule("piecewise", 1.0,
                          boundaries_and_scales={5: 0.1})
    assert float(piece(0)) == pytest.approx(1.0)
    assert float(piece(6)) == pytest.approx(0.1)

    const = make_schedule("constant", 0.3)
    assert float(const(999)) == pytest.approx(0.3)

    # Warmup composes with any schedule.
    wexp = make_schedule("exponential", 1.0, decay_steps=10,
                         decay_rate=0.5, warmup_steps=4)
    assert float(wexp(0)) == pytest.approx(0.0)
    assert float(wexp(2)) == pytest.approx(0.5)

    with pytest.raises(ValueError):
        make_schedule("cosine", 1.0)  # decay_steps required
    with pytest.raises(ValueError):
        make_schedule("piecewise", 1.0)  # would silently be constant
    with pytest.raises(ValueError):
        make_schedule("warmup_cosine", 1.0, decay_steps=10)  # needs warmup
    with pytest.raises(ValueError):
        make_schedule("nope", 1.0)

    # A callable passes through untouched.
    f = lambda step: 0.5  # noqa: E731
    assert make_schedule(f, 1.0) is f


def test_make_schedule_warmup_convention_total_horizon():
    """decay_steps counts the TOTAL horizon including warmup, uniformly.

    The optax building blocks disagree (warmup_cosine_decay_schedule's
    decay_steps includes warmup; a joined linear tail would not) — the
    factory normalizes to the include-warmup convention for every
    horizon-style schedule."""
    # linear: ends exactly at decay_steps, not decay_steps + warmup_steps.
    lin = make_schedule("linear", 1.0, decay_steps=10, warmup_steps=4,
                        end_value=0.0)
    assert float(lin(4)) == pytest.approx(1.0)   # warmup peak
    assert float(lin(7)) == pytest.approx(0.5)   # halfway through the tail
    assert float(lin(10)) == pytest.approx(0.0)  # done at the total horizon
    assert float(lin(14)) == pytest.approx(0.0)

    # piecewise: boundaries stay ABSOLUTE step indices under warmup.
    piece = make_schedule("piecewise", 1.0, warmup_steps=4,
                          boundaries_and_scales={6: 0.1})
    assert float(piece(5)) == pytest.approx(1.0)
    assert float(piece(7)) == pytest.approx(0.1)

    # Horizon-style schedules reject decay_steps <= warmup_steps ...
    with pytest.raises(ValueError):
        make_schedule("cosine", 1.0, decay_steps=4, warmup_steps=4)
    with pytest.raises(ValueError):
        make_schedule("linear", 1.0, decay_steps=3, warmup_steps=4)
    # ... and piecewise rejects boundaries inside the warmup window.
    with pytest.raises(ValueError):
        make_schedule("piecewise", 1.0, warmup_steps=4,
                      boundaries_and_scales={3: 0.1})


def test_trainer_with_cosine_schedule_decays_lr():
    trainer = Trainer(
        _tiny_model(), optimizer="sgd", learning_rate=0.1,
        lr_schedule="cosine",
        lr_schedule_options={"decay_steps": 8, "alpha": 0.01},
    )
    trainer.fit(_data(), epochs=2, steps_per_epoch=4, verbose=0)
    # inject_hyperparams records the LR *used* by the latest update, i.e.
    # sched(7) after 8 steps.
    expected = float(make_schedule("cosine", 0.1, decay_steps=8,
                                   alpha=0.01)(7))
    assert get_learning_rate(trainer.state) == pytest.approx(expected, rel=1e-3)
    assert expected < 0.03  # decayed well below the base LR
    assert np.isfinite(trainer.history.history["loss"][-1])


# --------------------------------------------------------------------- EMA
def test_ema_tracks_params_and_eval_uses_it():
    trainer = Trainer(
        _tiny_model(), optimizer="adam", learning_rate=5e-3, ema_decay=0.9,
    )
    data = _data()
    trainer.fit(data, epochs=1, steps_per_epoch=6, verbose=0)
    state = trainer.state
    assert state.ema_params is not None

    # The EMA lags the raw params (they started equal, so after steps they
    # differ but stay the same structure).
    diffs = jax.tree.map(
        lambda e, p: float(np.max(np.abs(np.asarray(e) - np.asarray(p)))),
        state.ema_params, state.params,
    )
    assert max(jax.tree.leaves(diffs)) > 0.0
    assert jax.tree.structure(state.ema_params) == jax.tree.structure(state.params)

    # evaluate() runs on the EMA weights and yields finite metrics.
    logs = trainer.evaluate(data, steps=2)
    assert np.isfinite(logs["loss"])

    # Sanity: eval_with_ema=False gives the raw-params numbers instead.
    raw_trainer = Trainer(
        _tiny_model(), optimizer="adam", learning_rate=5e-3,
        ema_decay=0.9, eval_with_ema=False,
    )
    raw_trainer.fit(data, epochs=1, steps_per_epoch=2, verbose=0)
    assert np.isfinite(raw_trainer.evaluate(data, steps=1)["loss"])


def test_ema_shadows_batch_stats_for_bn_eval():
    """BN models under EMA evaluate the EMA params against EMA-shadowed
    batch_stats, not the live moving statistics (VERDICT r2 weak #6:
    params-only shadowing skews BN eval)."""
    trainer = Trainer(
        _tiny_model(), optimizer="adam", learning_rate=5e-2, ema_decay=0.9,
    )
    data = _data()
    trainer.fit(data, epochs=1, steps_per_epoch=6, verbose=0)
    state = trainer.state
    assert state.ema_batch_stats is not None
    assert (jax.tree.structure(state.ema_batch_stats)
            == jax.tree.structure(state.batch_stats))
    # The shadow lags the live stats (equal at init, diverge with steps).
    lag = jax.tree.map(
        lambda e, p: float(np.max(np.abs(np.asarray(e) - np.asarray(p)))),
        state.ema_batch_stats, state.batch_stats,
    )
    assert max(jax.tree.leaves(lag)) > 0.0

    # The eval step really READS ema_batch_stats: corrupting the shadow
    # (zeros) must change the eval loss, which it could not if eval ran
    # against the live stats.
    loss_ema = trainer.evaluate(data, steps=2)["loss"]
    trainer.state = state.replace(
        ema_batch_stats=jax.tree.map(np.zeros_like, state.ema_batch_stats)
    )
    loss_zeroed = trainer.evaluate(data, steps=2)["loss"]
    assert loss_ema != loss_zeroed
    trainer.state = state


def test_no_ema_by_default():
    trainer = Trainer(_tiny_model(), optimizer="adam")
    trainer.fit(_data(), epochs=1, steps_per_epoch=1, verbose=0)
    assert trainer.state.ema_params is None


def test_ema_with_ps_sharded_state(mesh8):
    from pddl_tpu.parallel.ps import ParameterServerStrategy

    strategy = ParameterServerStrategy(min_shard_bytes=1 << 8)
    strategy._mesh = mesh8
    trainer = Trainer(
        _tiny_model(), optimizer="adam", learning_rate=1e-3,
        strategy=strategy, ema_decay=0.99,
    )
    trainer.fit(_data(batch=strategy.scale_batch_size(2)), epochs=1,
                steps_per_epoch=2, verbose=0)
    # EMA leaves carry the same shardings as their parameters.
    shard_of = lambda t: jax.tree.map(lambda x: x.sharding, t)  # noqa: E731
    assert shard_of(trainer.state.ema_params) == shard_of(trainer.state.params)


# ------------------------------------------------------- grad accumulation
def test_gradient_accumulation_matches_large_batch():
    """k micro-steps at accum=k == one step on the concatenated batch."""
    import flax.linen as nn
    import jax.numpy as jnp

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            return nn.Dense(8)(x.reshape(x.shape[0], -1))

    rng = np.random.default_rng(0)
    b1 = {"image": rng.normal(size=(4, 4, 4, 3)).astype(np.float32),
          "label": rng.integers(0, 8, 4).astype(np.int32)}
    b2 = {"image": rng.normal(size=(4, 4, 4, 3)).astype(np.float32),
          "label": rng.integers(0, 8, 4).astype(np.int32)}
    concat = {k: np.concatenate([b1[k], b2[k]]) for k in b1}

    acc = Trainer(Tiny(), optimizer="sgd", learning_rate=0.1,
                  gradient_accumulation_steps=2, seed=3)
    acc.fit([b1, b2], epochs=1, verbose=0)

    big = Trainer(Tiny(), optimizer="sgd", learning_rate=0.1, seed=3)
    big.fit([concat], epochs=1, verbose=0)

    for pa, pb in zip(jax.tree.leaves(acc.state.params),
                      jax.tree.leaves(big.state.params)):
        np.testing.assert_allclose(np.asarray(pa), np.asarray(pb),
                                   rtol=1e-5, atol=1e-6)


def test_ema_with_accumulation_matches_big_batch_ema():
    """EMA must decay once per optimizer update, not per micro-step."""
    import flax.linen as nn

    class Tiny(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            return nn.Dense(8)(x.reshape(x.shape[0], -1))

    rng = np.random.default_rng(1)
    b1 = {"image": rng.normal(size=(4, 4, 4, 3)).astype(np.float32),
          "label": rng.integers(0, 8, 4).astype(np.int32)}
    b2 = {"image": rng.normal(size=(4, 4, 4, 3)).astype(np.float32),
          "label": rng.integers(0, 8, 4).astype(np.int32)}
    concat = {k: np.concatenate([b1[k], b2[k]]) for k in b1}

    acc = Trainer(Tiny(), optimizer="sgd", learning_rate=0.1,
                  gradient_accumulation_steps=2, ema_decay=0.5, seed=3)
    acc.fit([b1, b2], epochs=1, verbose=0)
    big = Trainer(Tiny(), optimizer="sgd", learning_rate=0.1,
                  ema_decay=0.5, seed=3)
    big.fit([concat], epochs=1, verbose=0)
    for ea, eb in zip(jax.tree.leaves(acc.state.ema_params),
                      jax.tree.leaves(big.state.ema_params)):
        np.testing.assert_allclose(np.asarray(ea), np.asarray(eb),
                                   rtol=1e-5, atol=1e-6)


def test_lr_introspection_with_accumulation():
    from pddl_tpu.train.state import set_learning_rate

    trainer = Trainer(_tiny_model(), optimizer="adam", learning_rate=2e-3,
                      gradient_accumulation_steps=2)
    trainer.fit(_data(), epochs=1, steps_per_epoch=2, verbose=0)
    assert get_learning_rate(trainer.state) == pytest.approx(2e-3)
    trainer.state = set_learning_rate(trainer.state, 1e-4)
    assert get_learning_rate(trainer.state) == pytest.approx(1e-4)


# --------------------------------------------------------------------- CLI
def test_cli_schedule_and_ema_flags():
    from pddl_tpu.run import main

    rc = main([
        "--preset", "single", "--synthetic", "--model", "tiny_resnet",
        "--num-classes", "8", "--image-size", "32", "--batch", "4",
        "--epochs", "1", "--steps-per-epoch", "2", "--verbose", "0",
        "--lr-schedule", "cosine", "--lr-decay-steps", "4",
        "--ema-decay", "0.9", "--grad-accum", "2",
    ])
    assert rc == 0


# -------------------------------------------------------------- tensorboard
def test_tensorboard_callback_writes_events(tmp_path):
    tf = pytest.importorskip("tensorflow")
    from pddl_tpu.train.callbacks import TensorBoard

    log_dir = str(tmp_path / "tb")
    trainer = Trainer(_tiny_model(), optimizer="adam", learning_rate=1e-3)
    data = _data()
    trainer.fit(
        data, epochs=2, steps_per_epoch=2, verbose=0,
        validation_data=_data(seed=1), validation_steps=1,
        callbacks=[TensorBoard(log_dir)],
    )

    tags = {"train": set(), "validation": set()}
    for split in tags:
        files = glob.glob(os.path.join(log_dir, split, "events.out*"))
        assert files, f"no event files for {split}"
        for f in files:
            for ev in tf.compat.v1.train.summary_iterator(f):
                for v in ev.summary.value:
                    tags[split].add(v.tag)
    assert {"loss", "accuracy", "learning_rate"} <= tags["train"]
    assert {"loss", "accuracy"} <= tags["validation"]


def test_weight_decay_masks_biases_and_norms():
    """AdamW decay applies to matrices only: a zero-gradient step shrinks
    kernels but leaves biases/scales untouched (standard recipe)."""
    import jax.numpy as jnp

    from pddl_tpu.train.state import make_optimizer

    params = {
        "dense": {"kernel": jnp.ones((4, 4)), "bias": jnp.ones((4,))},
        "ln": {"scale": jnp.ones((4,))},
    }
    tx = make_optimizer("adamw", 1e-2, weight_decay=0.1)
    state = tx.init(params)
    zero_g = jax.tree.map(jnp.zeros_like, params)
    updates, _ = tx.update(zero_g, state, params)
    new = jax.tree.map(lambda p, u: p + u, params, updates)
    assert float(jnp.max(jnp.abs(new["dense"]["bias"] - 1))) == 0.0
    assert float(jnp.max(jnp.abs(new["ln"]["scale"] - 1))) == 0.0
    assert float(jnp.max(jnp.abs(new["dense"]["kernel"] - 1))) > 0.0

    # Plain "adamw" (no explicit weight_decay): optax's built-in default
    # decay (1e-4) must be masked identically.
    tx_plain = make_optimizer("adamw", 1e-2)
    u_p, _ = tx_plain.update(zero_g, tx_plain.init(params), params)
    new_p = jax.tree.map(lambda p, u: p + u, params, u_p)
    assert float(jnp.max(jnp.abs(new_p["dense"]["bias"] - 1))) == 0.0
    assert float(jnp.max(jnp.abs(new_p["dense"]["kernel"] - 1))) > 0.0

    # decay_mask alone (no explicit weight_decay) must also engage.
    only_kernel = lambda p: jax.tree.map(lambda x: x.ndim > 1, p)  # noqa: E731
    tx_m = make_optimizer("adamw", 1e-2, decay_mask=only_kernel)
    u_m, _ = tx_m.update(zero_g, tx_m.init(params), params)
    new_m = jax.tree.map(lambda p, u: p + u, params, u_m)
    assert float(jnp.max(jnp.abs(new_m["dense"]["bias"] - 1))) == 0.0

    # Explicit decay_mask=None restores decay-everything.
    tx_all = make_optimizer("adamw", 1e-2, weight_decay=0.1, decay_mask=None)
    u_all, _ = tx_all.update(zero_g, tx_all.init(params), params)
    new_all = jax.tree.map(lambda p, u: p + u, params, u_all)
    assert float(jnp.max(jnp.abs(new_all["dense"]["bias"] - 1))) > 0.0


def test_decay_mask_misuse_raises():
    import optax

    from pddl_tpu.train.state import make_optimizer

    with pytest.raises(ValueError, match="decay_mask"):
        make_optimizer("adam", 1e-3, decay_mask=lambda p: p)
    with pytest.raises(ValueError, match="decay_mask"):
        make_optimizer(optax.sgd(0.1), decay_mask=lambda p: p)


def test_bf16_params_keep_f32_hyperparams():
    """inject_hyperparams must NOT cast optimizer hyperparams to the
    params' storage dtype: in bf16, b2=0.999 rounds to exactly 1.0, the
    bias correction 1-b2^t becomes 0, and the first Adam update divides
    by zero — the whole tree NaNs in one step (found by the bf16-recipe
    convergence track)."""
    import jax.numpy as jnp
    import optax

    from pddl_tpu.train.state import _find_hyperparams, make_optimizer

    p = {"w": jnp.ones((4, 4), jnp.bfloat16) * 0.5,
         "b": jnp.zeros((4,), jnp.bfloat16)}
    g = jax.tree.map(lambda x: jnp.full_like(x, 1e-3), p)
    tx = make_optimizer("adamw", 3e-4)
    s = tx.init(p)
    hp = _find_hyperparams(s)
    assert hp is not None and hp["b2"].dtype == jnp.float32
    assert abs(float(hp["b2"]) - 0.999) < 1e-6  # NOT rounded to bf16's 1.0
    for _ in range(3):
        u, s = tx.update(g, s, p)
        p = optax.apply_updates(p, u)
    assert all(bool(jnp.isfinite(x).all()) for x in jax.tree.leaves(p))
