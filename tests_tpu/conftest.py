"""On-chip test harness: REAL TPU, Mosaic-compiled kernels.

The main suite (tests/conftest.py) pins an 8-device fake CPU mesh, which
forces every Pallas kernel through interpret mode (ops/attention.py:207)
— the Python interpreter of the kernel, not the compiled artifact. This
directory is the complement (VERDICT r2 weak #5): no platform pinning,
`interpret=False` forced at the call sites, and every test SKIPS unless
the default backend is a real TPU. Run on the bench chip:

    python -m pytest tests_tpu/ -q    # or: -m tpu

and commit the log under artifacts/tpu_pytest/.
"""

import jax
import pytest


def pytest_collection_modifyitems(config, items):
    for item in items:
        item.add_marker(pytest.mark.tpu)


@pytest.fixture(scope="session", autouse=True)
def require_tpu():
    if jax.default_backend() != "tpu":
        pytest.skip("tests_tpu/ needs a real TPU backend "
                    f"(got {jax.default_backend()!r})", allow_module_level=True)
