"""Mosaic-compiled kernel numerics vs oracles, on real TPU hardware.

The CPU suite proves the same assertions in interpret mode; these runs
close the interpret-vs-Mosaic gap for the Pallas flash kernel (fwd and
fused bwd), the chunked-CE custom VJP, and on-device augment
determinism. Tolerances are bf16/f32-mixed: the kernel accumulates in
f32 but inputs/outputs are bf16 (the TPU training configuration).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pddl_tpu.ops.attention import attention_reference, flash_attention
from pddl_tpu.ops.augment import standard_augment
from pddl_tpu.ops.large_vocab import chunked_cross_entropy


def _qkv(b=2, h=4, s=1024, d=64, dtype=jnp.bfloat16, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    shape = (b, h, s, d)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_forward_matches_reference_on_chip(causal):
    q, k, v = _qkv()
    out = jax.jit(
        lambda q, k, v: flash_attention(q, k, v, causal=causal,
                                        interpret=False)
    )(q, k, v)
    ref = jax.jit(
        lambda q, k, v: attention_reference(q, k, v, causal=causal)
    )(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        atol=2e-2, rtol=2e-2,  # bf16 outputs; f32 accumulation inside
    )


@pytest.mark.parametrize("causal", [False, True])
def test_flash_fused_backward_matches_reference_on_chip(causal):
    """The custom-VJP two-sweep backward (dq then dk/dv) vs AD through
    the O(S^2) reference — Mosaic-compiled, not interpreted."""
    q, k, v = _qkv(s=512)
    cot = jax.random.normal(jax.random.key(7), q.shape, jnp.float32)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, interpret=False)
        return jnp.sum(o.astype(jnp.float32) * cot)

    def loss_ref(q, k, v):
        o = attention_reference(q, k, v, causal=causal)
        return jnp.sum(o.astype(jnp.float32) * cot)

    gf = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
    gr = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            atol=5e-2, rtol=5e-2,
            err_msg=f"d{name} mismatch (causal={causal})",
        )


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gqa_matches_reference_on_chip(causal):
    """Mosaic-compiled GQA path (kv-head-aware index maps, K/V consumed
    unexpanded) fwd + fused bwd vs the expanded oracle — the llama-family
    training configuration (12 q-heads / 4 kv-heads at D=64)."""
    b, h, hkv, s, d = 2, 12, 4, 1024, 64
    ks = jax.random.split(jax.random.key(2), 3)
    q = jax.random.normal(ks[0], (b, h, s, d), jnp.bfloat16)
    k = jax.random.normal(ks[1], (b, hkv, s, d), jnp.bfloat16)
    v = jax.random.normal(ks[2], (b, hkv, s, d), jnp.bfloat16)

    def expand(t):
        return jnp.repeat(t, h // hkv, axis=1)

    out = jax.jit(lambda q, k, v: flash_attention(
        q, k, v, causal=causal, interpret=False))(q, k, v)
    ref = jax.jit(lambda q, k, v: attention_reference(
        q, expand(k), expand(v), causal=causal))(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        atol=2e-2, rtol=2e-2)

    cot = jax.random.normal(jax.random.key(7), q.shape, jnp.float32)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, interpret=False)
        return jnp.sum(o.astype(jnp.float32) * cot)

    def loss_ref(q, k, v):
        o = attention_reference(q, expand(k), expand(v), causal=causal)
        return jnp.sum(o.astype(jnp.float32) * cot)

    gf = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
    gr = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    for a, b_, name in zip(gf, gr, "qkv"):
        assert a.shape == b_.shape  # dk/dv at kv-head shape
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b_, np.float32),
            atol=5e-2, rtol=5e-2,
            err_msg=f"d{name} mismatch (causal={causal})")


def test_decode_attention_on_chip():
    """The serving sweep compiled on hardware: bf16 cache, grouped heads,
    ring buffer — vs the windowed oracle over the true history."""
    from pddl_tpu.ops.attention import decode_attention

    B, Hkv, rep, D = 1, 4, 3, 64
    H = Hkv * rep
    ring, window, T = 256, 200, 600
    ks = jax.random.split(jax.random.key(5), 3)
    keys = jax.random.normal(ks[0], (B, Hkv, T, D), jnp.bfloat16)
    vals = jax.random.normal(ks[1], (B, Hkv, T, D), jnp.bfloat16)
    q = jax.random.normal(ks[2], (B, H, 1, D), jnp.bfloat16)

    ref = attention_reference(q, keys, vals, causal=True, window=window,
                              k_offset=-(T - 1))
    slots = jnp.arange(T) % ring
    k_ring = jnp.zeros((B, Hkv, ring, D), jnp.bfloat16).at[:, :, slots].set(keys)
    v_ring = jnp.zeros((B, Hkv, ring, D), jnp.bfloat16).at[:, :, slots].set(vals)
    out = jax.jit(lambda q, k, v: decode_attention(
        q, k, v, jnp.int32(T - 1), window=window, rolling=True))(
            q, k_ring, v_ring)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        atol=3e-2, rtol=3e-2)


def test_chunked_ce_matches_materialized_logits_on_chip():
    """Loss AND grads of the never-materialize-logits head vs the full
    [T, V] logits path, at a vocab that actually chunks (3 scan steps)."""
    t, e, vocab, chunk = 256, 64, 1000, 384
    kf, kk, kl = jax.random.split(jax.random.key(1), 3)
    feats = jax.random.normal(kf, (t, e), jnp.float32)
    kernel = jax.random.normal(kk, (e, vocab), jnp.float32) * 0.02
    labels = jax.random.randint(kl, (t,), 0, vocab)

    def loss_chunked(feats, kernel):
        return chunked_cross_entropy(feats, kernel, labels,
                                     chunk_size=chunk)

    def loss_full(feats, kernel):
        logits = feats @ kernel
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.mean(jnp.take_along_axis(
            logp, labels[:, None], axis=-1))

    lc, gc = jax.jit(jax.value_and_grad(loss_chunked, argnums=(0, 1)))(
        feats, kernel)
    lf, gf = jax.jit(jax.value_and_grad(loss_full, argnums=(0, 1)))(
        feats, kernel)
    np.testing.assert_allclose(float(lc), float(lf), atol=1e-5, rtol=1e-5)
    for a, b, name in zip(gc, gf, ("features", "kernel")):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4,
            err_msg=f"d{name} mismatch",
        )


def test_augment_deterministic_on_chip():
    """Same rng -> bitwise-identical augmented batch on hardware (the
    race-detection stand-in: functional purity holds on the chip, not
    just under the CPU interpreter)."""
    aug = jax.jit(standard_augment(crop=224, flip=True))
    x = jax.random.uniform(jax.random.key(3), (8, 256, 256, 3)) * 255.0
    rng = jax.random.key(11)
    a = np.asarray(aug(rng, x))
    b = np.asarray(aug(rng, x))
    np.testing.assert_array_equal(a, b)
    # ...and a different key actually changes something (flip/crop live).
    c = np.asarray(aug(jax.random.key(12), x))
    assert (a != c).any()


def test_flash_sliding_window_matches_reference_on_chip():
    """Mosaic-compiled SWA (band block-skip + band mask) fwd+bwd vs the
    windowed O(S^2) reference, at an S/window where whole k-blocks skip."""
    q, k, v = _qkv(s=1024)
    cot = jax.random.normal(jax.random.key(7), q.shape, jnp.float32)
    w = 200  # unaligned to the 512x1024 default blocks

    out = jax.jit(lambda q, k, v: flash_attention(
        q, k, v, causal=True, window=w, block_q=256, block_k=256,
        interpret=False))(q, k, v)
    ref = jax.jit(lambda q, k, v: attention_reference(
        q, k, v, causal=True, window=w))(q, k, v)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        atol=2e-2, rtol=2e-2)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=True, window=w,
                            block_q=256, block_k=256, interpret=False)
        return jnp.sum(o.astype(jnp.float32) * cot)

    def loss_ref(q, k, v):
        o = attention_reference(q, k, v, causal=True, window=w)
        return jnp.sum(o.astype(jnp.float32) * cot)

    gf = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
    gr = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            atol=5e-2, rtol=5e-2, err_msg=f"d{name} (window={w})")


def test_speculative_greedy_consistent_on_chip():
    """The serving path, compiled on hardware. The CPU suite proves
    bit-exactness vs generate(); on the chip, the k+1-wide verify block
    and the one-token decode tick are DIFFERENT compiled programs whose
    bf16 logits legitimately differ by ulps — on an untrained model
    (near-uniform logits, ties everywhere) that can flip an argmax, so
    token strings may diverge while both remain valid greedy decodes.
    The hardware-honest invariant is GREEDY CONSISTENCY: every token the
    speculative path emitted must be an argmax-or-numerical-tie of the
    model's own conditional along the speculative output's OWN prefix
    (the trained-model chip benches additionally observe bit-equality,
    because trained logits have margins ulps can't cross)."""
    from pddl_tpu.models.llama import tiny_llama
    from pddl_tpu.models.speculative import generate_speculative

    model = tiny_llama(vocab_size=64, max_len=256,
                       dtype=jnp.bfloat16, param_dtype=jnp.bfloat16)
    prompt = (jnp.tile(jnp.arange(9, dtype=jnp.int32), (2, 6))[:, :48]
              % 64)
    variables = {"params": model.init(jax.random.key(0), prompt,
                                      train=False)["params"]}
    out, stats = generate_speculative(model, variables, prompt, 64,
                                      return_stats=True)
    assert stats["emitted"] == 64 and out.shape == (2, 112)
    logits = jax.jit(
        lambda v, t: model.apply(v, t, train=False))(variables, out[:, :-1])
    lg = np.asarray(logits, np.float32)
    tok = np.asarray(out)[:, 1:]
    sel = np.take_along_axis(lg, tok[..., None], axis=-1)[..., 0]
    gap = lg.max(axis=-1) - sel
    p = prompt.shape[1]
    # 0.1 is generous for bf16 ulp noise yet far below any real logit
    # margin at vocab 64 — a wrong (non-tie) token would blow this up.
    assert np.all(gap[:, p - 1:] < 0.1), float(gap[:, p - 1:].max())


def test_int8_serving_hook_on_chip():
    """Weight-only int8 through the compiled decode programs: the
    param_transform hook must reproduce dequantize-then-generate
    exactly (same weights, same math; only the jit boundary and the
    HBM representation move)."""
    from pddl_tpu.models.gpt import generate, tiny_gpt
    from pddl_tpu.models.speculative import generate_speculative
    from pddl_tpu.ops.quant import dequantize, quantize_int8

    model = tiny_gpt(vocab_size=64, max_len=256,
                     dtype=jnp.bfloat16, param_dtype=jnp.bfloat16)
    prompt = jnp.tile(jnp.arange(7, dtype=jnp.int32), (1, 6))[:, :40]
    params = model.init(jax.random.key(1), prompt, train=False)["params"]
    qparams = quantize_int8(params, min_elems=128)
    ref = generate(model, {"params": dequantize(qparams)}, prompt,
                   max_new_tokens=48)
    # Plain generate: the hook moves only the jit boundary and the HBM
    # representation, the compiled program is otherwise the same — this
    # leg stays BIT-equal.
    out = generate(model, {"params": qparams}, prompt, max_new_tokens=48,
                   param_transform=dequantize)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
    # Speculative leg: the k+1-wide verify block and the one-token tick
    # are DIFFERENT compiled programs whose bf16 logits can differ by
    # ulps — on an untrained model that can flip an argmax at a genuine
    # tie (see test_speculative_greedy_consistent_on_chip), so assert
    # GREEDY CONSISTENCY along the speculative output's own prefix
    # against the dequantized model's conditional, not bit-equality.
    out_spec = generate_speculative(model, {"params": qparams}, prompt,
                                    48, param_transform=dequantize)
    logits = jax.jit(
        lambda p, t: model.apply({"params": p}, t, train=False))(
            dequantize(qparams), out_spec[:, :-1])
    lg = np.asarray(logits, np.float32)
    tok = np.asarray(out_spec)[:, 1:]
    sel = np.take_along_axis(lg, tok[..., None], axis=-1)[..., 0]
    gap = lg.max(axis=-1) - sel
    p = prompt.shape[1]
    assert np.all(gap[:, p - 1:] < 0.1), float(gap[:, p - 1:].max())
